"""TPC-H query acceptance suite: the engine vs pandas oracles over the
seeded mini database (qa_nightly / NDS-style acceptance — SURVEY §4.2;
exercises multi-joins, semi joins, string predicates, group-by, having,
top-k in one place)."""

import datetime

import numpy as np
import pandas as pd
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


@pytest.fixture(scope="module")
def db(session):
    from spark_rapids_tpu.models.tpch import gen_tables
    tables = gen_tables()
    dfs = {k: session.create_dataframe(t) for k, t in tables.items()}
    pds = {k: t.to_pandas() for k, t in tables.items()}
    return dfs, pds


def _rows(df):
    return df.collect()


def _close(got, exp, places=6):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, e in zip(got, exp):
        assert len(g) == len(e), (g, e)
        for a, b in zip(g, e):
            if isinstance(b, float):
                assert a == pytest.approx(b, rel=10 ** -places), (g, e)
            else:
                assert a == b, (g, e)


def test_q3_shipping_priority(db):
    f = F()
    dfs, pds = db
    seg, cutoff = "BUILDING", datetime.date(1995, 3, 15)
    q = (dfs["customer"].filter(f.col("c_mktsegment") == seg)
         .join(dfs["orders"], on=[("c_custkey", "o_custkey")])
         .filter(f.col("o_orderdate") < cutoff)
         .join(dfs["lineitem"], on=[("o_orderkey", "l_orderkey")])
         .filter(f.col("l_shipdate") > cutoff)
         .select("o_orderkey", "o_orderdate", "o_shippriority",
                 (f.col("l_extendedprice") * (1 - f.col("l_discount")))
                 .alias("volume"))
         .group_by("o_orderkey", "o_orderdate", "o_shippriority")
         .agg(f.sum(f.col("volume")).alias("revenue"))
         .sort(f.col("revenue").desc(), f.col("o_orderkey"))
         .limit(10))
    got = _rows(q.select("o_orderkey", "revenue"))

    c = pds["customer"]; o = pds["orders"]; l = pds["lineitem"]
    m = (c[c.c_mktsegment == seg]
         .merge(o[o.o_orderdate < cutoff], left_on="c_custkey",
                right_on="o_custkey")
         .merge(l[l.l_shipdate > cutoff], left_on="o_orderkey",
                right_on="l_orderkey"))
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby(["o_orderkey", "o_orderdate", "o_shippriority"])
           ["volume"].sum().reset_index()
           .sort_values(["volume", "o_orderkey"],
                        ascending=[False, True]).head(10))
    _close(got, list(zip(exp.o_orderkey.astype(int), exp.volume)))


def test_q4_order_priority_semi_join(db):
    f = F()
    dfs, pds = db
    lo = datetime.date(1993, 7, 1)
    hi = datetime.date(1993, 10, 1)
    late = dfs["lineitem"].filter(
        f.col("l_commitdate") < f.col("l_receiptdate"))
    q = (dfs["orders"]
         .filter((f.col("o_orderdate") >= lo) & (f.col("o_orderdate") < hi))
         .join(late, on=[("o_orderkey", "l_orderkey")], how="semi")
         .group_by("o_orderpriority")
         .agg(f.count_star().alias("order_count"))
         .sort("o_orderpriority"))
    got = _rows(q)

    o = pds["orders"]; l = pds["lineitem"]
    late_keys = set(l.loc[l.l_commitdate < l.l_receiptdate, "l_orderkey"])
    sub = o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)
            & o.o_orderkey.isin(late_keys)]
    exp = (sub.groupby("o_orderpriority").size().reset_index(name="n")
           .sort_values("o_orderpriority"))
    _close(got, list(zip(exp.o_orderpriority, exp.n.astype(int))))


def test_q5_local_supplier_volume(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    q = (dfs["customer"]
         .join(dfs["orders"], on=[("c_custkey", "o_custkey")])
         .filter((f.col("o_orderdate") >= lo) & (f.col("o_orderdate") < hi))
         .join(dfs["lineitem"], on=[("o_orderkey", "l_orderkey")])
         .join(dfs["supplier"], on=[("l_suppkey", "s_suppkey")])
         .filter(f.col("c_nationkey") == f.col("s_nationkey"))
         .join(dfs["nation"], on=[("s_nationkey", "n_nationkey")])
         .join(dfs["region"].filter(f.col("r_name") == "ASIA"),
               on=[("n_regionkey", "r_regionkey")])
         .select("n_name",
                 (f.col("l_extendedprice") * (1 - f.col("l_discount")))
                 .alias("volume"))
         .group_by("n_name").agg(f.sum(f.col("volume")).alias("revenue"))
         .sort(f.col("revenue").desc()))
    got = _rows(q)

    c, o, l, s, n, r = (pds[k] for k in
                        ["customer", "orders", "lineitem", "supplier",
                         "nation", "region"])
    m = (c.merge(o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)],
                 left_on="c_custkey", right_on="o_custkey")
         .merge(l, left_on="o_orderkey", right_on="l_orderkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey"))
    m = m[m.c_nationkey == m.s_nationkey]
    m = (m.merge(n, left_on="s_nationkey", right_on="n_nationkey")
         .merge(r[r.r_name == "ASIA"], left_on="n_regionkey",
                right_on="r_regionkey"))
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby("n_name")["volume"].sum().reset_index()
           .sort_values("volume", ascending=False))
    _close(got, list(zip(exp.n_name, exp.volume)))


def test_q10_returned_items(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1993, 10, 1), datetime.date(1994, 1, 1)
    q = (dfs["customer"]
         .join(dfs["orders"], on=[("c_custkey", "o_custkey")])
         .filter((f.col("o_orderdate") >= lo) & (f.col("o_orderdate") < hi))
         .join(dfs["lineitem"].filter(f.col("l_returnflag") == "R"),
               on=[("o_orderkey", "l_orderkey")])
         .select("c_custkey", "c_name", "c_acctbal",
                 (f.col("l_extendedprice") * (1 - f.col("l_discount")))
                 .alias("volume"))
         .group_by("c_custkey", "c_name", "c_acctbal")
         .agg(f.sum(f.col("volume")).alias("revenue"))
         .sort(f.col("revenue").desc(), f.col("c_custkey")).limit(20))
    got = _rows(q.select("c_custkey", "revenue"))

    c, o, l = pds["customer"], pds["orders"], pds["lineitem"]
    m = (c.merge(o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)],
                 left_on="c_custkey", right_on="o_custkey")
         .merge(l[l.l_returnflag == "R"], left_on="o_orderkey",
                right_on="l_orderkey"))
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby(["c_custkey", "c_name", "c_acctbal"])["volume"]
           .sum().reset_index()
           .sort_values(["volume", "c_custkey"],
                        ascending=[False, True]).head(20))
    _close(got, list(zip(exp.c_custkey.astype(int), exp.volume)))


def test_q12_shipmode(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    high = f.when(f.col("o_orderpriority").isin("1-URGENT", "2-HIGH"),
                  f.lit(1)).otherwise(f.lit(0))
    low = f.when(~f.col("o_orderpriority").isin("1-URGENT", "2-HIGH"),
                 f.lit(1)).otherwise(f.lit(0))
    q = (dfs["orders"]
         .join(dfs["lineitem"]
               .filter(f.col("l_shipmode").isin("MAIL", "SHIP")
                       & (f.col("l_commitdate") < f.col("l_receiptdate"))
                       & (f.col("l_shipdate") < f.col("l_commitdate"))
                       & (f.col("l_receiptdate") >= lo)
                       & (f.col("l_receiptdate") < hi)),
               on=[("o_orderkey", "l_orderkey")])
         .select("l_shipmode", high.alias("high"), low.alias("low"))
         .group_by("l_shipmode")
         .agg(f.sum(f.col("high")).alias("high_line_count"),
              f.sum(f.col("low")).alias("low_line_count"))
         .sort("l_shipmode"))
    got = _rows(q)

    o, l = pds["orders"], pds["lineitem"]
    sub = l[l.l_shipmode.isin(["MAIL", "SHIP"])
            & (l.l_commitdate < l.l_receiptdate)
            & (l.l_shipdate < l.l_commitdate)
            & (l.l_receiptdate >= lo) & (l.l_receiptdate < hi)]
    m = o.merge(sub, left_on="o_orderkey", right_on="l_orderkey")
    m["high"] = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    m["low"] = 1 - m["high"]
    exp = (m.groupby("l_shipmode")[["high", "low"]].sum().reset_index()
           .sort_values("l_shipmode"))
    _close(got, list(zip(exp.l_shipmode, exp.high.astype(int),
                         exp.low.astype(int))))


def test_q14_promo_effect(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1995, 9, 1), datetime.date(1995, 10, 1)
    vol = f.col("l_extendedprice") * (1 - f.col("l_discount"))
    q = (dfs["lineitem"]
         .filter((f.col("l_shipdate") >= lo) & (f.col("l_shipdate") < hi))
         .join(dfs["part"], on=[("l_partkey", "p_partkey")])
         .select(f.when(f.col("p_type").like("PROMO%"), vol)
                 .otherwise(f.lit(0.0)).alias("promo"),
                 vol.alias("total"))
         .agg(f.sum(f.col("promo")).alias("p"),
              f.sum(f.col("total")).alias("t")))
    p, t = _rows(q)[0]

    l, pt = pds["lineitem"], pds["part"]
    m = (l[(l.l_shipdate >= lo) & (l.l_shipdate < hi)]
         .merge(pt, left_on="l_partkey", right_on="p_partkey"))
    m["vol"] = m.l_extendedprice * (1 - m.l_discount)
    exp_p = m.loc[m.p_type.str.startswith("PROMO"), "vol"].sum()
    exp_t = m.vol.sum()
    assert p == pytest.approx(exp_p) and t == pytest.approx(exp_t)


def test_q18_large_volume_customer_having(db):
    f = F()
    dfs, pds = db
    big = (dfs["lineitem"].group_by("l_orderkey")
           .agg(f.sum(f.col("l_quantity")).alias("qty"))
           .filter(f.col("qty") > 300))  # HAVING
    q = (dfs["orders"]
         .join(big, on=[("o_orderkey", "l_orderkey")], how="semi")
         .join(dfs["customer"], on=[("o_custkey", "c_custkey")])
         .select("c_name", "o_orderkey", "o_totalprice")
         .sort(f.col("o_totalprice").desc(), f.col("o_orderkey")).limit(10))
    got = _rows(q.select("o_orderkey", "o_totalprice"))

    o, l, c = pds["orders"], pds["lineitem"], pds["customer"]
    qty = l.groupby("l_orderkey")["l_quantity"].sum()
    keys = set(qty[qty > 300].index)
    sub = o[o.o_orderkey.isin(keys)].merge(
        c, left_on="o_custkey", right_on="c_custkey")
    exp = sub.sort_values(["o_totalprice", "o_orderkey"],
                          ascending=[False, True]).head(10)
    _close(got, list(zip(exp.o_orderkey.astype(int), exp.o_totalprice)))


def test_q19_disjunctive_predicates(db):
    f = F()
    dfs, pds = db
    q = (dfs["lineitem"]
         .join(dfs["part"], on=[("l_partkey", "p_partkey")])
         .filter(
             (f.col("p_container").isin("SM CASE", "SM BOX")
              & (f.col("l_quantity") >= 1) & (f.col("l_quantity") <= 20)
              & (f.col("p_size") <= 15))
             | (f.col("p_container").isin("MED BAG", "MED BOX")
                & (f.col("l_quantity") >= 10) & (f.col("l_quantity") <= 30)
                & (f.col("p_size") <= 25)))
         .agg(f.sum(f.col("l_extendedprice") * (1 - f.col("l_discount")))
              .alias("revenue")))
    got = _rows(q)[0][0]

    l, pt = pds["lineitem"], pds["part"]
    m = l.merge(pt, left_on="l_partkey", right_on="p_partkey")
    keep = ((m.p_container.isin(["SM CASE", "SM BOX"])
             & (m.l_quantity >= 1) & (m.l_quantity <= 20) & (m.p_size <= 15))
            | (m.p_container.isin(["MED BAG", "MED BOX"])
               & (m.l_quantity >= 10) & (m.l_quantity <= 30)
               & (m.p_size <= 25)))
    exp = (m.loc[keep, "l_extendedprice"]
           * (1 - m.loc[keep, "l_discount"])).sum()
    assert got == pytest.approx(exp)


def test_q1_and_q6_on_minidb(db):
    """The two bench queries also run against the mini DB oracles."""
    from spark_rapids_tpu.models import tpch
    dfs, pds = db
    got_q6 = tpch.q6(dfs["lineitem"]).collect()[0][0]
    exp_q6 = tpch.q6_pandas(pds["lineitem"])
    assert (got_q6 or 0.0) == pytest.approx(exp_q6)
    got_q1 = tpch.q1(dfs["lineitem"]).collect()
    exp_q1 = tpch.q1_pandas(pds["lineitem"])
    assert len(got_q1) == len(exp_q1)
    for g, (_, e) in zip(got_q1, exp_q1.iterrows()):
        assert g[0] == e.l_returnflag and g[1] == e.l_linestatus
        assert g[2] == pytest.approx(e.sum_qty)
        assert g[5] == pytest.approx(e.sum_charge)
        assert g[9] == e.count_order


def test_q7_volume_shipping(db):
    """Q7 shape: supplier/customer nation pair volumes by year."""
    f = F()
    dfs, pds = db
    n1, n2 = "FRANCE", "GERMANY"
    lo = datetime.date(1995, 1, 1)
    hi = datetime.date(1996, 12, 31)
    sup_n = dfs["nation"].filter(f.col("n_name").isin(n1, n2)) \
        .select(f.col("n_nationkey").alias("sn_key"),
                f.col("n_name").alias("supp_nation"))
    cust_n = dfs["nation"].filter(f.col("n_name").isin(n1, n2)) \
        .select(f.col("n_nationkey").alias("cn_key"),
                f.col("n_name").alias("cust_nation"))
    q = (dfs["supplier"].join(sup_n, on=[("s_nationkey", "sn_key")])
         .join(dfs["lineitem"], on=[("s_suppkey", "l_suppkey")])
         .filter((f.col("l_shipdate") >= lo) & (f.col("l_shipdate") <= hi))
         .join(dfs["orders"], on=[("l_orderkey", "o_orderkey")])
         .join(dfs["customer"], on=[("o_custkey", "c_custkey")])
         .join(cust_n, on=[("c_nationkey", "cn_key")])
         .filter(((f.col("supp_nation") == n1)
                  & (f.col("cust_nation") == n2))
                 | ((f.col("supp_nation") == n2)
                    & (f.col("cust_nation") == n1)))
         .select("supp_nation", "cust_nation",
                 f.year(f.col("l_shipdate")).alias("l_year"),
                 (f.col("l_extendedprice") * (1 - f.col("l_discount")))
                 .alias("volume"))
         .group_by("supp_nation", "cust_nation", "l_year")
         .agg(f.sum(f.col("volume")).alias("revenue"))
         .sort("supp_nation", "cust_nation", "l_year"))
    got = _rows(q)

    s, l, o, c, n = (pds[k] for k in
                     ["supplier", "lineitem", "orders", "customer",
                      "nation"])
    nn = n[n.n_name.isin([n1, n2])]
    m = (s.merge(nn.rename(columns={"n_nationkey": "sn_key",
                                    "n_name": "supp_nation"})[
        ["sn_key", "supp_nation"]], left_on="s_nationkey",
        right_on="sn_key")
         .merge(l[(l.l_shipdate >= lo) & (l.l_shipdate <= hi)],
                left_on="s_suppkey", right_on="l_suppkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey")
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(nn.rename(columns={"n_nationkey": "cn_key",
                                   "n_name": "cust_nation"})[
             ["cn_key", "cust_nation"]], left_on="c_nationkey",
             right_on="cn_key"))
    m = m[((m.supp_nation == n1) & (m.cust_nation == n2))
          | ((m.supp_nation == n2) & (m.cust_nation == n1))]
    m["l_year"] = pd.to_datetime(m.l_shipdate).dt.year
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby(["supp_nation", "cust_nation", "l_year"])["volume"]
           .sum().reset_index()
           .sort_values(["supp_nation", "cust_nation", "l_year"]))
    _close(got, [(r.supp_nation, r.cust_nation, int(r.l_year), r.volume)
                 for r in exp.itertuples()])


def test_q9_product_type_profit(db):
    """Q9 shape: profit by nation and year over a 5-way join with a
    LIKE part filter."""
    f = F()
    dfs, pds = db
    q = (dfs["part"].filter(f.col("p_name").like("%goldenrod%"))
         .join(dfs["lineitem"], on=[("p_partkey", "l_partkey")])
         .join(dfs["supplier"], on=[("l_suppkey", "s_suppkey")])
         .join(dfs["nation"], on=[("s_nationkey", "n_nationkey")])
         .join(dfs["orders"], on=[("l_orderkey", "o_orderkey")])
         .select(f.col("n_name").alias("nation"),
                 f.year(f.col("o_orderdate")).alias("o_year"),
                 (f.col("l_extendedprice") * (1 - f.col("l_discount"))
                  - f.lit(0.01) * f.col("l_quantity")).alias("amount"))
         .group_by("nation", "o_year")
         .agg(f.sum(f.col("amount")).alias("sum_profit"))
         .sort("nation", f.col("o_year").desc()))
    got = _rows(q)

    pt, l, s, n, o = (pds[k] for k in
                      ["part", "lineitem", "supplier", "nation", "orders"])
    m = (pt[pt.p_name.str.contains("goldenrod")]
         .merge(l, left_on="p_partkey", right_on="l_partkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(n, left_on="s_nationkey", right_on="n_nationkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey"))
    m["o_year"] = pd.to_datetime(m.o_orderdate).dt.year
    m["amount"] = (m.l_extendedprice * (1 - m.l_discount)
                   - 0.01 * m.l_quantity)
    exp = (m.groupby(["n_name", "o_year"])["amount"].sum().reset_index()
           .sort_values(["n_name", "o_year"], ascending=[True, False]))
    _close(got, [(r.n_name, int(r.o_year), r.amount)
                 for r in exp.itertuples()])

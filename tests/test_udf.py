"""UDF tests: device (tpu_udf / RapidsUDF analog) and CPU Python UDFs."""

import math

import numpy as np
import pyarrow as pa
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_tpu_udf_runs_on_device(session):
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    f = F()

    @f.tpu_udf(return_type=T.FLOAT64)
    def gelu(x):
        return 0.5 * x * (1.0 + jnp.tanh(0.7978845608 * (x + 0.044715 * x**3)))

    df = session.create_dataframe({"x": [0.0, 1.0, -1.0, 2.5]})
    out = df.select(gelu(f.col("x")).alias("g"))
    plan = out.explain_string()
    assert not any(ln.strip().startswith("!") for ln in plan.splitlines()[2:]), plan
    got = [r[0] for r in out.collect()]
    exp = [0.5 * x * (1 + math.tanh(0.7978845608 * (x + 0.044715 * x**3)))
           for x in [0.0, 1.0, -1.0, 2.5]]
    np.testing.assert_allclose(got, exp, rtol=1e-6)


def test_tpu_udf_null_propagation(session):
    from spark_rapids_tpu import types as T
    f = F()
    double_it = f.tpu_udf(lambda x: x * 2, return_type=T.FLOAT64, name="dbl")
    df = session.create_dataframe({"x": [1.0, None, 3.0]})
    got = [r[0] for r in df.select(double_it(f.col("x")).alias("y")).collect()]
    assert got == [2.0, None, 6.0]


def test_python_udf_falls_back_with_reason(session):
    from spark_rapids_tpu import types as T
    f = F()

    @f.udf(return_type=T.INT64)
    def weird(x):
        if x is None:
            return None
        return int(str(int(x))[::-1])  # digit reversal: opaque to any planner

    df = session.create_dataframe({"x": [123, 450, None]})
    out = df.select(weird(f.col("x")).alias("r"))
    plan = out.explain_string()
    assert "python UDF" in plan and "CPU" in plan
    got = [r[0] for r in out.collect()]
    assert got == [321, 54, None]


def test_python_udf_two_args(session):
    from spark_rapids_tpu import types as T
    f = F()
    fmt = f.udf(lambda a, b: None if a is None or b is None else a * 10 + b,
                return_type=T.INT64, name="combine")
    df = session.create_dataframe({"a": [1, 2, None], "b": [5, None, 7]})
    got = [r[0] for r in df.select(fmt(f.col("a"), f.col("b")).alias("c"))
           .collect()]
    assert got == [15, None, None]


def test_tpu_udf_composes_with_exprs(session):
    from spark_rapids_tpu import types as T
    f = F()
    sq = f.tpu_udf(lambda x: x * x, return_type=T.FLOAT64, name="sq")
    df = session.create_dataframe({"x": [1.0, 2.0, 3.0, 4.0]})
    out = df.filter(f.col("x") > 1.5) \
            .select((sq(f.col("x")) + f.lit(1.0)).alias("y")) \
            .agg(f.sum(f.col("y")).alias("s"))
    assert out.collect()[0][0] == (4.0 + 1) + (9.0 + 1) + (16.0 + 1)


def test_pandas_udf_vectorized(session):
    import pandas as pd
    from spark_rapids_tpu import types as T
    f = F()

    @f.pandas_udf(return_type=T.FLOAT64)
    def zscore(s):
        return (s - s.mean()) / s.std(ddof=0)

    df = session.create_dataframe({"x": [1.0, 2.0, 3.0, 4.0]})
    out = df.select(zscore(f.col("x")).alias("z"))
    plan = out.explain_string()
    assert "python UDF" in plan  # CPU with reason, like opaque UDFs
    got = [r[0] for r in out.collect()]
    import numpy as np
    exp = (np.array([1, 2, 3, 4.0]) - 2.5) / np.std([1, 2, 3, 4.0])
    np.testing.assert_allclose(got, exp, rtol=1e-12)


def test_pandas_udf_two_series_with_nulls(session):
    from spark_rapids_tpu import types as T
    f = F()
    add = f.pandas_udf(lambda a, b: a + b, return_type=T.FLOAT64)
    df = session.create_dataframe({"a": [1.0, None, 3.0],
                                   "b": [10.0, 20.0, None]})
    got = [r[0] for r in df.select(add(f.col("a"), f.col("b")).alias("c"))
           .collect()]
    assert got == [11.0, None, None]


class TestWorkerIsolation:
    """python/rapids/daemon.py analog: UDF batches run in a forked
    worker; crashes and hangs surface as PythonWorkerError while the
    engine process survives."""

    def _sess(self, fresh_session):
        fresh_session.conf.set(
            "spark.rapids.tpu.python.worker.isolation", True)
        return fresh_session

    def test_isolated_udf_computes(self, fresh_session):
        sess = self._sess(fresh_session)
        import pyarrow as pa
        from spark_rapids_tpu.udf import udf
        from spark_rapids_tpu import types as T
        f = udf(lambda x: None if x is None else x * 3 + 1,
                return_type=T.INT64, try_compile=False)
        df = sess.create_dataframe(pa.table({"v": pa.array([1, 2, None],
                                                           type=pa.int64())}))
        got = [r[0] for r in df.select(f("v").alias("o")).collect()]
        assert got == [4, 7, None]

    def test_crashing_udf_is_contained(self, fresh_session):
        sess = self._sess(fresh_session)
        import os
        import pyarrow as pa
        import pytest as _pt
        from spark_rapids_tpu.udf import PythonWorkerError, udf
        from spark_rapids_tpu import types as T

        def boom(x):
            os._exit(42)  # hard process death, not an exception

        f = udf(boom, return_type=T.INT64, try_compile=False)
        df = sess.create_dataframe(pa.table({"v": pa.array([1, 2])}))
        with _pt.raises(PythonWorkerError, match="died"):
            df.select(f("v").alias("o")).collect()
        # the engine process survives and keeps working
        assert df.count() == 2

    def test_hanging_udf_times_out(self, fresh_session):
        sess = self._sess(fresh_session)
        sess.conf.set("spark.rapids.tpu.python.worker.timeout", 1.0)
        import time as _t
        import pyarrow as pa
        import pytest as _pt
        from spark_rapids_tpu.udf import PythonWorkerError, udf
        from spark_rapids_tpu import types as T

        def sleepy(x):
            _t.sleep(60)
            return x

        f = udf(sleepy, return_type=T.INT64, try_compile=False)
        df = sess.create_dataframe(pa.table({"v": pa.array([1])}))
        with _pt.raises(PythonWorkerError, match="timed out"):
            df.select(f("v").alias("o")).collect()

    def test_raising_udf_reports(self, fresh_session):
        sess = self._sess(fresh_session)
        import pyarrow as pa
        import pytest as _pt
        from spark_rapids_tpu.udf import PythonWorkerError, udf
        from spark_rapids_tpu import types as T

        def bad(x):
            raise ValueError("nope")

        f = udf(bad, return_type=T.INT64, try_compile=False)
        df = sess.create_dataframe(pa.table({"v": pa.array([1])}))
        with _pt.raises(PythonWorkerError, match="nope"):
            df.select(f("v").alias("o")).collect()

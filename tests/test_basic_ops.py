"""Project/filter/expression tests vs pandas oracle
(integration_tests arithmetic_ops_test.py / cmp_test.py analogs)."""

import numpy as np
import pandas as pd
import pytest

from .support import (DoubleGen, IntGen, LongGen, BoolGen, StringGen,
                      assert_df_matches_pandas, gen_table, pdf_rows,
                      assert_rows_equal)


@pytest.fixture(scope="module")
def num_df(session, rng):
    table, pdf = gen_table(rng, {
        "a": IntGen(lo=-1000, hi=1000),
        "b": IntGen(lo=-1000, hi=1000, nullable=False),
        # float columns stay non-nullable in generated tables (see
        # support.gen_table); dedicated literal tests cover float nulls
        "x": DoubleGen(nullable=False, special=True),
        "y": DoubleGen(nullable=False, special=True),
        "l": LongGen(lo=-(2**40), hi=2**40),
        "flag": BoolGen(),
    }, 500)
    return session.create_dataframe(table), pdf


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_project_arithmetic(num_df):
    df, pdf = num_df
    f = F()
    out = df.select(
        (f.col("a") + f.col("b")).alias("s"),
        (f.col("a") * 2).alias("d"),
        (f.col("x") - f.col("y")).alias("diff"),
        (-f.col("b")).alias("neg"),
    )
    exp = pd.DataFrame({
        "s": pdf.a + pdf.b,
        "d": pdf.a * 2,
        "diff": pdf.x - pdf.y,
        "neg": -pdf.b,
    })
    assert_df_matches_pandas(out, exp, ignore_order=False)


def test_division_null_on_zero(session):
    f = F()
    df = session.create_dataframe({"a": [1.0, 2.0, 3.0, 4.0],
                                   "b": [2.0, 0.0, -1.0, 0.0]})
    out = df.select((f.col("a") / f.col("b")).alias("q")).collect()
    assert out == [(0.5,), (None,), (-3.0,), (None,)]


def test_remainder_sign(session):
    f = F()
    df = session.create_dataframe({"a": [7, -7, 7, -7], "b": [3, 3, -3, 0]})
    out = df.select((f.col("a") % f.col("b")).alias("m")).collect()
    assert out == [(1,), (-1,), (1,), (None,)]


def test_comparisons_and_filter(num_df):
    df, pdf = num_df
    f = F()
    out = df.where((f.col("a") > 0) & (f.col("x") < 100.0))
    m = (pdf.a > 0) & (pdf.x < 100.0)
    exp = pdf[m.fillna(False)]
    assert_df_matches_pandas(out, exp)


def test_filter_or_with_nulls(session):
    f = F()
    df = session.create_dataframe(
        {"a": pd.array([1, None, 3, None], dtype="Int64"),
         "b": pd.array([None, 2, None, 4], dtype="Int64")})
    out = df.where((f.col("a") > 0) | (f.col("b") > 3)).collect()
    assert sorted(r[0] is not None and r[0] or -1 for r in out) == [-1, 1, 3]


def test_null_predicates(session):
    f = F()
    df = session.create_dataframe(
        {"a": pd.array([1, None, 3], dtype="Int64")})
    out = df.select(f.col("a").is_null().alias("n"),
                    f.col("a").is_not_null().alias("nn")).collect()
    assert out == [(False, True), (True, False), (False, True)]


def test_case_when_if(session):
    f = F()
    df = session.create_dataframe({"a": [1, 2, 3, 4, 5]})
    out = df.select(
        f.when(f.col("a") < 2, "low")
         .when(f.col("a") < 4, "mid")
         .otherwise("high").alias("bucket")).collect()
    assert [r[0] for r in out] == ["low", "mid", "mid", "high", "high"]


def test_coalesce(session):
    f = F()
    df = session.create_dataframe(
        {"a": pd.array([None, 2, None], dtype="Int64"),
         "b": pd.array([10, None, None], dtype="Int64")})
    out = df.select(f.coalesce(f.col("a"), f.col("b"), f.lit(-1)).alias("c"))
    assert [r[0] for r in out.collect()] == [10, 2, -1]


def test_in_and_between(session):
    f = F()
    df = session.create_dataframe({"a": [1, 2, 3, 4, 5]})
    out = df.where(f.col("a").isin(2, 4)).collect()
    assert [r[0] for r in out] == [2, 4]
    out2 = df.where(f.col("a").between(2, 4)).collect()
    assert [r[0] for r in out2] == [2, 3, 4]


def test_cast_int_double_bool(session):
    f = F()
    df = session.create_dataframe({"a": [1, 0, -3]})
    out = df.select(f.col("a").cast("double").alias("d"),
                    f.col("a").cast("boolean").alias("b"),
                    f.col("a").cast("bigint").alias("l")).collect()
    assert out == [(1.0, True, 1), (0.0, False, 0), (-3.0, True, -3)]


def test_cast_double_to_int_truncates(session):
    f = F()
    df = session.create_dataframe({"x": [1.9, -1.9, float("nan"), 2.0]})
    out = df.select(f.col("x").cast("int").alias("i")).collect()
    assert out == [(1,), (-1,), (0,), (2,)]


def test_chained_project_filter_fusion(num_df):
    df, pdf = num_df
    f = F()
    out = (df.select((f.col("a") + f.col("b")).alias("s"), "x")
             .where(f.col("s") % 2 == 0)
             .select((f.col("s") * f.col("x")).alias("sx")))
    # python-level oracle: pandas extension (Int64) arithmetic silently
    # converts a float NaN operand (x has specials) into pd.NA, conflating
    # the NaN VALUE with SQL null — Spark/engine semantics keep NaN
    vals = []
    for ai, bi, xi in zip(pdf.a, pdf.b, pdf.x):
        if pd.isna(ai) or pd.isna(bi):
            continue
        s = int(ai) + int(bi)
        if s % 2 != 0:
            continue
        vals.append(float(s) * float(xi))
    exp = pd.DataFrame({"sx": pd.Series(vals, dtype="float64")})
    assert_df_matches_pandas(out, exp, approx_float=True)


def test_string_passthrough_and_fallback(session):
    f = F()
    df = session.create_dataframe({"s": ["a", "b", None, "d"],
                                   "v": [1, 2, 3, 4]})
    out = df.select("s", (f.col("v") * 10).alias("v10")).collect()
    assert out == [("a", 10), ("b", 20), (None, 30), ("d", 40)]
    # string equality filter → CPU fallback path
    out2 = df.where(f.col("s") == "b").collect()
    assert out2 == [("b", 2)]


def test_limit_offset(session):
    df = session.range(100)
    assert [r[0] for r in df.limit(5).collect()] == [0, 1, 2, 3, 4]


def test_union_distinct(session):
    df1 = session.create_dataframe({"a": [1, 2, 3]})
    df2 = session.create_dataframe({"a": [3, 4]})
    out = df1.union(df2).distinct().collect()
    assert sorted(r[0] for r in out) == [1, 2, 3, 4]

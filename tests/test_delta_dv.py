"""Delta deletion vectors + column mapping.

Reference: the Delta protocol's deletion-vector format (RoaringBitmapArray
+ Z85 descriptors + DV store framing) read by the reference through its
delta-lake modules (GpuDeltaParquetFileFormat row filtering), and
columnMapping mode ``name`` (physical parquet names mapped to logical).
"""

import json
import os
import uuid

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.io import deletion_vectors as dvs
from spark_rapids_tpu.io.delta import (delta_delete, delta_update,
                                       read_delta, write_delta)
from spark_rapids_tpu.sql import functions as F


class TestZ85:
    def test_roundtrip(self):
        for n in (4, 8, 16, 40):
            data = bytes(range(n))
            enc = dvs.z85_encode(data)
            assert len(enc) == n // 4 * 5
            assert dvs.z85_decode(enc) == data

    def test_uuid_path(self):
        u = uuid.uuid4()
        rel = dvs.dv_relative_path(dvs.encode_uuid_path(u, "ab/"))
        assert rel == f"ab/deletion_vector_{u}.bin"
        rel2 = dvs.dv_relative_path(dvs.encode_uuid_path(u))
        assert rel2 == f"deletion_vector_{u}.bin"


class TestRoaring:
    @pytest.mark.parametrize("rows", [
        [],
        [0],
        [0, 1, 2, 65535, 65536, 131072],          # multiple containers
        list(range(5000)),                         # bitmap container
        [2**32 - 1, 2**32, 2**33 + 7],             # multiple 32-bit maps
        list(range(0, 200000, 3)),                 # mixed array+bitmap
    ])
    def test_roundtrip(self, rows):
        data = dvs.serialize_roaring_array(np.array(rows, dtype=np.int64))
        got = dvs.deserialize_roaring_array(data)
        np.testing.assert_array_equal(got, np.unique(rows).astype(np.int64))

    def test_magic_checked(self):
        with pytest.raises(ValueError, match="magic"):
            dvs.deserialize_roaring_array(b"\x00" * 16)

    def test_run_container_decodes(self):
        """Hand-build a 12347-cookie bitmap with one run container —
        real writers emit runs; our reader must accept them."""
        import struct
        # one container, run flag set, runs [(10, len 4)] -> 10..14
        cookie = (12347 | (0 << 16))
        buf = struct.pack("<i", cookie) + bytes([0b1])
        buf += struct.pack("<HH", 0, 4)      # key 0, cardinality-1 = 4
        buf += struct.pack("<H", 1)           # 1 run
        buf += struct.pack("<HH", 10, 4)      # start 10, length 4
        arr = struct.pack("<iq", dvs.MAGIC, 1) + buf
        got = dvs.deserialize_roaring_array(arr)
        np.testing.assert_array_equal(got, np.arange(10, 15))

    def test_dv_file_roundtrip(self, tmp_path):
        rows = np.array([1, 5, 9, 70000], dtype=np.int64)
        desc, abs_path = dvs.write_dv_file(str(tmp_path), rows)
        assert desc["storageType"] == "u"
        assert desc["cardinality"] == 4
        assert os.path.exists(abs_path)
        got = dvs.read_dv(str(tmp_path), desc)
        np.testing.assert_array_equal(got, rows)

    def test_inline_descriptor(self, tmp_path):
        rows = np.array([3, 4, 5], dtype=np.int64)
        data = dvs.serialize_roaring_array(rows)
        pad = (-len(data)) % 4
        desc = {"storageType": "i",
                "pathOrInlineDv": dvs.z85_encode(data + b"\x00" * pad),
                "sizeInBytes": len(data), "cardinality": 3}
        got = dvs.read_dv(str(tmp_path), desc)
        np.testing.assert_array_equal(got, rows)


class TestDeleteWithDV:
    def _table(self, session, tmp_path, n=100):
        path = str(tmp_path / "t")
        df = session.create_dataframe({
            "id": np.arange(n), "v": np.arange(n) * 1.0})
        write_delta(df, path)
        return path

    def test_dv_delete_filters_reads(self, session, tmp_path):
        path = self._table(session, tmp_path)
        v = delta_delete(session, path, F.col("id") % F.lit(10) == F.lit(0),
                         use_dv=True)
        assert v == 1
        got = sorted(r[0] for r in session.read_delta(path)
                     .select("id").collect())
        assert got == [i for i in range(100) if i % 10 != 0]
        # the data file was NOT rewritten (merge-on-read)
        logf = os.path.join(path, "_delta_log",
                            f"{1:020d}.json")
        actions = [json.loads(l) for l in open(logf) if l.strip()]
        add = next(a["add"] for a in actions if "add" in a)
        assert add["deletionVector"]["cardinality"] == 10
        assert any("protocol" in a for a in actions)

    def test_dv_deletes_accumulate(self, session, tmp_path):
        path = self._table(session, tmp_path)
        delta_delete(session, path, F.col("id") < F.lit(10), use_dv=True)
        delta_delete(session, path, F.col("id") >= F.lit(90), use_dv=True)
        got = sorted(r[0] for r in session.read_delta(path)
                     .select("id").collect())
        assert got == list(range(10, 90))
        # second DV is cumulative over the same file
        logf = os.path.join(path, "_delta_log", f"{2:020d}.json")
        actions = [json.loads(l) for l in open(logf) if l.strip()]
        add = next(a["add"] for a in actions if "add" in a)
        assert add["deletionVector"]["cardinality"] == 20
        # the protocol upgrade happens once, not per commit
        assert not any("protocol" in a for a in actions)

    def test_dv_multi_row_group_offsets(self, session, tmp_path):
        """DV positions are raw-file row indexes; a multi-row-group file
        with pruned groups must still map them correctly."""
        path = str(tmp_path / "mrg")
        t = pa.table({"id": np.arange(1000), "v": np.arange(1000) * 1.0})
        os.makedirs(path)
        pq.write_table(t, os.path.join(path, "part-0.parquet"),
                       row_group_size=100)  # 10 row groups
        from spark_rapids_tpu.io.delta import _commit
        os.makedirs(os.path.join(path, "_delta_log"), exist_ok=True)
        meta = {"metaData": {
            "id": "m", "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps({"type": "struct", "fields": [
                {"name": "id", "type": "long", "nullable": True,
                 "metadata": {}},
                {"name": "v", "type": "double", "nullable": True,
                 "metadata": {}}]}),
            "partitionColumns": [], "configuration": {}}}
        with open(os.path.join(path, "_delta_log",
                               f"{0:020d}.json"), "w") as f:
            f.write(json.dumps(meta) + "\n")
            f.write(json.dumps({"add": {
                "path": "part-0.parquet", "partitionValues": {},
                "size": 1, "modificationTime": 0,
                "dataChange": True}}) + "\n")
        # delete every row ending in 7
        delta_delete(session, path, F.col("id") % F.lit(10) == F.lit(7),
                     use_dv=True)
        # predicate prunes to late row groups; offsets must still line up
        got = sorted(r[0] for r in session.read_delta(path)
                     .filter(F.col("id") >= F.lit(850)).select("id")
                     .collect())
        assert got == [i for i in range(850, 1000) if i % 10 != 7]

    def test_time_travel_predates_dv(self, session, tmp_path):
        path = self._table(session, tmp_path)
        delta_delete(session, path, F.col("id") < F.lit(50), use_dv=True)
        assert session.read_delta(path).count() == 50
        assert session.read_delta(path, version=0).count() == 100

    def test_full_file_delete_removes_file(self, session, tmp_path):
        path = self._table(session, tmp_path, n=10)
        delta_delete(session, path, F.lit(True), use_dv=True)
        with pytest.raises(FileNotFoundError, match="no data files"):
            read_delta(path)

    def test_rewrite_update_respects_dv(self, session, tmp_path):
        """UPDATE (copy-on-write) after a DV delete must not resurrect
        DV-deleted rows."""
        path = self._table(session, tmp_path, n=20)
        delta_delete(session, path, F.col("id") < F.lit(5), use_dv=True)
        delta_update(session, path, {"v": F.lit(0.0)},
                     F.col("id") >= F.lit(15))
        rows = sorted(session.read_delta(path).collect())
        assert [r[0] for r in rows] == list(range(5, 20))
        assert all(r[1] == 0.0 for r in rows if r[0] >= 15)

    def test_dv_with_predicate_pushdown(self, session, tmp_path):
        path = self._table(session, tmp_path)
        delta_delete(session, path, F.col("id") < F.lit(30), use_dv=True)
        got = sorted(r[0] for r in session.read_delta(path)
                     .filter(F.col("id") < F.lit(60)).select("id").collect())
        assert got == list(range(30, 60))


def _write_column_mapped_table(path: str, frames):
    """Hand-build a columnMapping=name table: parquet files use physical
    col-<n> names; the Delta schema maps them to logical names."""
    os.makedirs(os.path.join(path, "_delta_log"), exist_ok=True)
    phys = {"id": "col-1a", "v": "col-2b"}
    fields = []
    for i, (logical, p) in enumerate(phys.items()):
        fields.append({
            "name": logical,
            "type": "long" if logical == "id" else "double",
            "nullable": True,
            "metadata": {"delta.columnMapping.id": i + 1,
                         "delta.columnMapping.physicalName": p}})
    meta = {"metaData": {
        "id": str(uuid.uuid4()),
        "format": {"provider": "parquet", "options": {}},
        "schemaString": json.dumps({"type": "struct", "fields": fields}),
        "partitionColumns": [],
        "configuration": {"delta.columnMapping.mode": "name",
                          "delta.columnMapping.maxColumnId": "2"}}}
    actions = [meta]
    for i, t in enumerate(frames):
        rel = f"part-{i:05d}.parquet"
        pq.write_table(
            t.rename_columns([phys[c] for c in t.column_names]),
            os.path.join(path, rel))
        actions.append({"add": {
            "path": rel, "partitionValues": {},
            "size": os.path.getsize(os.path.join(path, rel)),
            "modificationTime": 0, "dataChange": True}})
    with open(os.path.join(path, "_delta_log", f"{0:020d}.json"), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")


class TestColumnMapping:
    def test_read_maps_physical_to_logical(self, session, tmp_path):
        path = str(tmp_path / "cm")
        _write_column_mapped_table(path, [
            pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0]}),
            pa.table({"id": [4, 5], "v": [4.0, 5.0]})])
        df = session.read_delta(path)
        assert df.columns == ["id", "v"]
        got = sorted(df.filter(F.col("id") > F.lit(2)).collect())
        assert got == [(3, 3.0), (4, 4.0), (5, 5.0)]

    def test_dv_delete_on_mapped_table(self, session, tmp_path):
        path = str(tmp_path / "cm")
        _write_column_mapped_table(path, [
            pa.table({"id": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]})])
        delta_delete(session, path, F.col("id") <= F.lit(2), use_dv=True)
        got = sorted(session.read_delta(path).collect())
        assert got == [(3, 3.0), (4, 4.0)]
        # the protocol upgrade must CARRY the columnMapping feature — a
        # protocol action replaces the previous one wholesale
        logf = os.path.join(path, "_delta_log", f"{1:020d}.json")
        actions = [json.loads(l) for l in open(logf) if l.strip()]
        proto = next(a["protocol"] for a in actions if "protocol" in a)
        assert "columnMapping" in proto["readerFeatures"]
        assert "deletionVectors" in proto["readerFeatures"]

    def test_rewrite_on_mapped_table_rejected(self, session, tmp_path):
        path = str(tmp_path / "cm")
        _write_column_mapped_table(path, [
            pa.table({"id": [1], "v": [1.0]})])
        with pytest.raises(NotImplementedError, match="column-mapped"):
            delta_update(session, path, {"v": F.lit(9.0)})

"""Test bootstrap: force an 8-device CPU JAX platform.

The driver validates multi-chip sharding on a virtual CPU mesh
(xla_force_host_platform_device_count), so the unit suite runs on 8 virtual
CPU devices.  A TPU plugin may already be registered at interpreter start (the
axon sitecustomize does this); registration is harmless — what matters is
selecting the cpu platform and setting XLA_FLAGS *before the first backend
initialization*, which this conftest does at import time.

Set SRT_TESTS_ON_TPU=1 to run the suite against the real TPU instead.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("SRT_TESTS_ON_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu", (
        "tests must run on the CPU platform; a backend was already "
        "initialized before conftest ran")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def session():
    import spark_rapids_tpu as srt
    return srt.Session.get_or_create()


@pytest.fixture()
def fresh_session():
    import spark_rapids_tpu as srt
    srt.Session.reset()
    s = srt.Session.get_or_create()
    yield s
    srt.Session.reset()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(20260729)


def pytest_collection_modifyitems(config, items):
    """Collection-time static analysis: ONE cached srtlint scan
    (tools/srtlint — AST engine, thirteen passes over a single shared
    parse) replaces the five regex lints that each re-read the whole
    tree here.  The scan is keyed by per-file CONTENT hashes: an
    unchanged tree re-verifies in milliseconds, and a changed tree
    re-verifies incrementally (only edited files + passes whose scope
    the edit touches re-run); any unsuppressed finding fails the run
    before a single test executes.  Rule docs: python -m tools.srtlint
    --explain <rule>, or docs/static_analysis.md."""
    from tools.srtlint import run_for_pytest
    report = run_for_pytest()
    if report.failing:
        lines = "\n".join(
            f"  {f.path}:{f.line}: [{f.rule}] {f.message}"
            for f in report.failing)
        raise pytest.UsageError(
            "srtlint found invariant violations (python -m tools.srtlint"
            f" --explain <rule> for the contract):\n{lines}")

"""Test bootstrap: force an 8-device CPU JAX platform.

The driver validates multi-chip sharding on a virtual CPU mesh
(xla_force_host_platform_device_count), so the unit suite runs on 8 virtual
CPU devices.  A TPU plugin may already be registered at interpreter start (the
axon sitecustomize does this); registration is harmless — what matters is
selecting the cpu platform and setting XLA_FLAGS *before the first backend
initialization*, which this conftest does at import time.

Set SRT_TESTS_ON_TPU=1 to run the suite against the real TPU instead.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("SRT_TESTS_ON_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu", (
        "tests must run on the CPU platform; a backend was already "
        "initialized before conftest ran")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def session():
    import spark_rapids_tpu as srt
    return srt.Session.get_or_create()


@pytest.fixture()
def fresh_session():
    import spark_rapids_tpu as srt
    srt.Session.reset()
    s = srt.Session.get_or_create()
    yield s
    srt.Session.reset()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(20260729)


def pytest_collection_modifyitems(config, items):
    """Collection-time lints: (a) a raw jax.device_get / np.asarray(
    <col>.data) in the operator layer dodges the metrics choke point and
    silently corrupts the sync profile; (b) a raw clock read in the
    exec-node layer bypasses the span API, so profiled EXPLAIN and the
    trace export silently lose that time — fail the run before any test
    executes."""
    from tools.check_blocking_fetch import check
    violations = check()
    if violations:
        lines = "\n".join(f"  spark_rapids_tpu/{rel}:{ln}: {src}"
                          for rel, ln, src in violations)
        raise pytest.UsageError(
            "raw device->host transfers outside utils.metrics.fetch/"
            f"fetch_async (tools/check_blocking_fetch.py):\n{lines}")
    from tools.check_span_timing import check as check_timing
    violations = check_timing()
    if violations:
        lines = "\n".join(f"  spark_rapids_tpu/{rel}:{ln}: {src}"
                          for rel, ln, src in violations)
        raise pytest.UsageError(
            "raw clock reads bypassing the span API — use MetricSet.time"
            " or utils.tracing.span (tools/check_span_timing.py):\n"
            f"{lines}")
    # (c) a worker thread created without joining the query's
    # contextvars escapes per-query stats/trace/cancellation
    from tools.check_ctx_threads import check as check_threads
    violations = check_threads()
    if violations:
        lines = "\n".join(f"  spark_rapids_tpu/{rel}:{ln}: {src}"
                          for rel, ln, src in violations)
        raise pytest.UsageError(
            "threads that don't join query contextvars — run work via "
            "contextvars.copy_context() or mark '# ctx-ok' "
            f"(tools/check_ctx_threads.py):\n{lines}")
    # (d) cross-query cache keys built anywhere but cache/keys.py would
    # let the identity rules diverge between tiers — silent wrong-data
    # hits, the worst failure mode a cache has
    from tools.check_cache_keys import check as check_keys
    violations = check_keys()
    if violations:
        lines = "\n".join(f"  spark_rapids_tpu/{rel}:{ln}: {src}"
                          for rel, ln, src in violations)
        raise pytest.UsageError(
            "ad-hoc cache keys — derive them via cache.keys.scan_key / "
            f"broadcast_key (tools/check_cache_keys.py):\n{lines}")
    # (e) a bare `except Exception: pass` swallows the transient faults
    # the recovery framework exists to retry/account, and a hand-rolled
    # sleep-after-except retry loop dodges backoff, budgets, and stats
    from tools.check_fault_paths import check as check_faults
    violations = check_faults()
    if violations:
        lines = "\n".join(f"  spark_rapids_tpu/{rel}:{ln}: {src}"
                          for rel, ln, src in violations)
        raise pytest.UsageError(
            "swallowed faults / ad-hoc retry loops — use faults.recovery."
            "transient_retry or mark '# fault-ok' "
            f"(tools/check_fault_paths.py):\n{lines}")

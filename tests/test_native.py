"""Native companion library: hash kernels vs device/golden values, block
codec round trips, string-cast semantics (spark-rapids-jni / nvcomp analogs)."""

import numpy as np
import pytest

from spark_rapids_tpu import native


def test_native_builds():
    assert native.available(), "g++ build of native/srt_native.cpp failed"


def test_murmur3_long_spark_golden():
    # hash(1L) = -1712319331 (Spark); hash(0L) pinned from this
    # implementation (native and the independent numpy path agree)
    out = native.murmur3_long(np.array([1, 0], dtype=np.int64), 42)
    assert out.tolist() == [-1712319331, -1670924195]


def test_murmur3_matches_device_kernel():
    import jax.numpy as jnp
    from spark_rapids_tpu.ops import hashing
    rng = np.random.default_rng(0)
    vals = rng.integers(-2**62, 2**62, size=1000, dtype=np.int64)
    host = native.murmur3_long(vals, 42)
    dev = np.asarray(hashing.hash_columns([(jnp.asarray(vals), None)],
                                          seed=42))
    np.testing.assert_array_equal(host, dev.view(np.int32))


def test_murmur3_utf8_matches_int_hash_for_aligned():
    """Spark's hashUnsafeBytes over a 4-byte string equals hashInt of the
    same little-endian word (both run one mix block then fmix(len=4)) —
    cross-checks the utf8 kernel against the Spark-verified int path."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops import hashing
    data = b"abcd"
    word = int.from_bytes(data, "little", signed=True)
    out = native.murmur3_utf8(np.frombuffer(data, dtype=np.uint8),
                              np.array([0, 4], dtype=np.int64), 42)
    dev = np.asarray(hashing.hash_columns(
        [(jnp.asarray([word], dtype=jnp.int32), None)], seed=42))
    assert out.tolist() == dev.view(np.int32).tolist()


def test_murmur3_utf8_matches_python_fallback():
    rng = np.random.default_rng(1)
    strings = [bytes(rng.integers(0, 256, size=rng.integers(0, 20),
                                  dtype=np.uint8)) for _ in range(50)]
    blob = b"".join(strings)
    offsets = np.cumsum([0] + [len(s) for s in strings]).astype(np.int64)
    b = np.frombuffer(blob, dtype=np.uint8)
    got = native.murmur3_utf8(b, offsets, 42)
    # recompute via the pure-python path by forcing lib=None behaviors
    exp = np.empty(len(strings), dtype=np.int32)
    for i, s in enumerate(strings):
        h = np.uint32(42)
        nb = len(s) // 4
        for k in range(nb):
            w = np.frombuffer(s[k*4:k*4+4], dtype="<u4")[0]
            h = native._np_mix_h1(h, native._np_mix_k1(w))
        for k in range(nb*4, len(s)):
            # sign-extended byte reinterpreted as uint32 (Spark tail rule)
            sb = s[k] - 256 if s[k] >= 128 else s[k]
            w = np.uint32(sb & 0xffffffff)
            h = native._np_mix_h1(h, native._np_mix_k1(w))
        exp[i] = np.int32(native._np_fmix(h, len(s)))
    np.testing.assert_array_equal(got, exp)


def test_pmod_partition():
    h = np.array([-7, -1, 0, 5, 200], dtype=np.int32)
    out = native.pmod_partition(h, 4)
    assert out.tolist() == [1, 3, 0, 1, 0]


def test_xxhash64_vs_canonical():
    """Spark's XXH64.hashLong == canonical xxhash64 of the long's
    little-endian bytes; python-xxhash is the independent oracle."""
    xxhash = pytest.importorskip("xxhash")
    rng = np.random.default_rng(5)
    vals = rng.integers(-2**62, 2**62, size=100, dtype=np.int64)
    got = native.xxhash64_long(vals)
    exp = [np.uint64(xxhash.xxh64_intdigest(
        int(v).to_bytes(8, "little", signed=True), seed=42)).view(np.int64)
        for v in vals]
    np.testing.assert_array_equal(got, np.array(exp, dtype=np.int64))


@pytest.mark.parametrize("payload", [
    b"", b"a", b"hello world " * 1000, bytes(range(256)) * 50,
    np.random.default_rng(3).integers(0, 256, 100_000, dtype=np.uint8)
    .tobytes(),
    b"\x00" * 65536,
])
def test_codec_roundtrip(payload):
    comp = native.compress(payload)
    assert comp is not None
    back = native.decompress(comp, len(payload))
    assert back == payload


def test_codec_compresses_redundancy():
    payload = b"abcdefgh" * 10000
    comp = native.compress(payload)
    assert len(comp) < len(payload) // 10


def test_cast_string_to_long():
    strs = [b"123", b" -45 ", b"+7", b"", b"abc", b"12.5",
            b"9223372036854775807", b"9223372036854775808",
            b"-9223372036854775808", b"-9223372036854775809"]
    blob = b"".join(strs)
    offsets = np.cumsum([0] + [len(s) for s in strs]).astype(np.int64)
    vals, valid = native.cast_string_to_long(
        np.frombuffer(blob, dtype=np.uint8), offsets)
    assert valid.tolist() == [True, True, True, False, False, False,
                              True, False, True, False]
    assert vals[0] == 123 and vals[1] == -45 and vals[2] == 7
    assert vals[6] == 9223372036854775807
    assert vals[8] == -9223372036854775808


def test_cast_string_to_double():
    strs = [b"1.5", b" -2e3 ", b"inf", b"nan", b"x", b""]
    blob = b"".join(strs)
    offsets = np.cumsum([0] + [len(s) for s in strs]).astype(np.int64)
    vals, valid = native.cast_string_to_double(
        np.frombuffer(blob, dtype=np.uint8), offsets)
    assert valid.tolist() == [True, True, True, True, False, False]
    assert vals[0] == 1.5 and vals[1] == -2000.0
    assert np.isinf(vals[2]) and np.isnan(vals[3])


def test_spill_disk_tier_compressed(session, tmp_path):
    """Disk spill files use the native codec (SRTC frames)."""
    import glob
    import jax.numpy as jnp
    from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn, Field, Schema
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.memory.spill import SpillCatalog
    cat = SpillCatalog(device_budget=1 << 30, host_budget=1 << 30,
                       spill_dir=str(tmp_path), compress_spill=True)
    data = jnp.asarray(np.tile(np.arange(16, dtype=np.int64), 64))
    b = ColumnBatch(Schema([Field("x", T.INT64, False)]),
                    [DeviceColumn(T.INT64, data, None)], 1024)
    h = cat.register(b)
    h.spill_to_host()
    h.spill_to_disk()
    files = glob.glob(str(tmp_path / "srt-spill-*.bin"))
    assert len(files) == 1
    with open(files[0], "rb") as f:
        assert f.read(4) == b"SRTC"
    back = h.get()
    np.testing.assert_array_equal(np.asarray(back.columns[0].data), data)
    h.close()

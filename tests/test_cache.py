"""Cross-query device cache (spark_rapids_tpu/cache/): differential
correctness on the TPC-H slice, write invalidation, refcounted eviction
under concurrency, spill demotion under a tiny budget, and leak
hygiene.

The cache's contract: a hit is INDISTINGUISHABLE from a re-scan (same
rows, same bytes), entries a query holds are never dropped from under
it, memory pressure demotes cache bytes to host via the spill catalog
(priority below live query state) instead of OOMing anyone, and every
write path drops entries sourced from the written table.
"""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.cache import (broadcast_key, clear_query_cache,
                                    get_query_cache, scan_key)
from spark_rapids_tpu.cache.device_cache import QueryCache
from spark_rapids_tpu.memory.spill import (PRIORITY_CACHE, SpillableBatch,
                                           get_catalog)
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.utils.metrics import QueryStats


@pytest.fixture()
def cached_session():
    s = srt.Session.get_or_create()
    s.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
    clear_query_cache()
    yield s
    s.conf.unset("spark.rapids.tpu.sql.cache.enabled")
    for k in ("spark.rapids.tpu.sql.cache.maxBytes",
              "spark.rapids.tpu.sql.cache.ttlMs",
              "spark.rapids.tpu.join.denseMinProbeRows"):
        s.conf.unset(k)
    clear_query_cache()


def _write_pq(tmp_path, name, pdf):
    path = str(tmp_path / name)
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), path)
    return path


def _frame(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "a": np.arange(n, dtype=np.int64),
        "b": rng.random(n),
        "k": rng.integers(0, 16, n).astype(np.int64),
    })


# ---------------------------------------------------------------------------------
# differential correctness: the full TPC-H slice, cached == uncached
# ---------------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_db(tmp_path_factory, session):
    from spark_rapids_tpu.models import tpch_suite
    out = str(tmp_path_factory.mktemp("tpch_cache"))
    paths = tpch_suite.gen_db(0.01, out)
    return paths


@pytest.mark.parametrize("qname", ["q1", "q3", "q5", "q6", "q14", "q19"])
def test_tpch_differential_cached_vs_uncached(cached_session, tpch_db,
                                              qname):
    """Oracle-exact under the cache: the cold (populating) run, the warm
    (hitting) run, and the cache-off run return byte-identical rows."""
    from spark_rapids_tpu.models import tpch_suite
    s = cached_session
    runner, _oracle = tpch_suite.QUERIES[qname]
    dfs = {t: s.read_parquet(tpch_db[t]) for t in tpch_suite.TABLES[qname]}

    cold = runner(dfs)
    qc = get_query_cache()
    warm = runner(dfs)
    assert qc.hits > 0, "warm run never hit the cache"
    s.conf.set("spark.rapids.tpu.sql.cache.enabled", False)
    try:
        off = runner(dfs)
    finally:
        s.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
    assert cold == warm == off


def test_tpch_differential_after_write_invalidate(cached_session,
                                                  tmp_path):
    """The acceptance cycle: populate, overwrite the table, and the next
    run reflects the NEW data (no stale hit)."""
    s = cached_session
    pdf = _frame(2000, seed=11)
    d = str(tmp_path / "tbl")
    s.create_dataframe(pdf).write.mode("overwrite").parquet(d)
    df = s.read_parquet(d)
    r1 = df.agg(F.sum(F.col("a")).alias("s")).collect()[0][0]
    assert r1 == int(pdf["a"].sum())
    qc = get_query_cache()
    assert qc.entry_count() > 0

    pdf2 = _frame(500, seed=12)
    s.create_dataframe(pdf2).write.mode("overwrite").parquet(d)
    assert qc.entry_count() == 0, "overwrite must invalidate"
    df2 = s.read_parquet(d)
    r2 = df2.agg(F.sum(F.col("a")).alias("s")).collect()[0][0]
    assert r2 == int(pdf2["a"].sum())


def test_append_invalidates(cached_session, tmp_path):
    s = cached_session
    pdf = _frame(1000, seed=21)
    d = str(tmp_path / "tbl")
    s.create_dataframe(pdf).write.mode("overwrite").parquet(d)
    df = s.read_parquet(d)
    assert df.agg(F.count(F.col("a")).alias("n")).collect()[0][0] == 1000
    qc = get_query_cache()
    n_before = qc.entry_count()
    assert n_before > 0
    s.create_dataframe(_frame(100, seed=22)).write.mode(
        "append").parquet(d)
    assert qc.entry_count() == 0, "append must invalidate (file set grew)"
    df2 = s.read_parquet(d)
    assert df2.agg(F.count(F.col("a")).alias("n")).collect()[0][0] == 1100


# ---------------------------------------------------------------------------------
# partial projection hits
# ---------------------------------------------------------------------------------

def test_partial_projection_hit_slices(cached_session, tmp_path):
    s = cached_session
    pdf = _frame(3000, seed=5)
    path = _write_pq(tmp_path, "t.parquet", pdf)
    df = s.read_parquet(path)
    wide = df.select("a", "b", "k").collect()
    assert len(wide) == 3000
    qc = get_query_cache()
    snap = qc.snapshot()
    got = df.select("k", "a").collect()
    snap2 = qc.snapshot()
    assert snap2["hits"] == snap["hits"] + 1, "superset entry must serve"
    assert snap2["entries"] == snap["entries"], "no re-upload, no new entry"
    exp = [(int(k), int(a)) for a, k in zip(pdf["a"], pdf["k"])]
    assert [tuple(r) for r in got] == exp


# ---------------------------------------------------------------------------------
# broadcast build reuse
# ---------------------------------------------------------------------------------

def test_broadcast_build_reuse_skips_stats_fetches(cached_session,
                                                   tmp_path):
    """A warm broadcast-join run hits all three reuse points (both scans
    + the build) and pays no MORE blocking fetches than the cold run —
    the cached entry carries the probed dense stats."""
    s = cached_session
    s.conf.set("spark.rapids.tpu.join.denseMinProbeRows", 0)
    fact = _write_pq(tmp_path, "fact.parquet", _frame(8000, seed=7))
    dim = _write_pq(tmp_path, "dim.parquet", pd.DataFrame({
        "k2": np.arange(16, dtype=np.int64),
        "w": np.linspace(1.0, 2.0, 16)}))
    fdf, ddf = s.read_parquet(fact), s.read_parquet(dim)
    q = lambda: (fdf.join(ddf, on=[("k", "k2")])
                 .agg(F.sum(F.col("b") * F.col("w")).alias("x")).collect())
    QueryStats.reset()
    before = QueryStats.get().snapshot()
    cold = q()
    cold_stats = QueryStats.delta_since(before)
    before = QueryStats.get().snapshot()
    warm = q()
    warm_stats = QueryStats.delta_since(before)
    assert cold == warm
    # fact scan + build (the dim scan rides INSIDE the cached build)
    assert warm_stats["cache_hits"] >= 2, warm_stats
    assert warm_stats["blocking_fetches"] <= \
        max(1, cold_stats["blocking_fetches"] - 2), (
            "broadcast reuse must skip the build's stats fetches:"
            f" cold={cold_stats['blocking_fetches']}"
            f" warm={warm_stats['blocking_fetches']}")


# ---------------------------------------------------------------------------------
# refcounts, budget eviction, spill demotion
# ---------------------------------------------------------------------------------

def _mini_batch(n=256, fill=1):
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import (ColumnBatch, DeviceColumn, Field,
                                        Schema)
    sch = Schema([Field("x", T.INT64, False)])
    col = DeviceColumn(T.INT64, jnp.full((n,), fill, dtype=jnp.int64))
    return ColumnBatch(sch, [col], n)


def _key_for(tmp_path, name, cached_session):
    """A real scan key (derived through the central helper, as the lint
    demands) pointing at a throwaway parquet file."""
    path = _write_pq(tmp_path, name, pd.DataFrame(
        {"x": np.arange(4, dtype=np.int64)}))
    src = cached_session.read_parquet(path)._plan.source
    return scan_key(src, 1024, "cpu:0")


def test_refcounted_eviction_no_use_after_evict(cached_session, tmp_path):
    cache = QueryCache(max_bytes=1 << 12)  # tiny: one entry fits
    k1 = _key_for(tmp_path, "a.parquet", cached_session)
    k2 = _key_for(tmp_path, "b.parquet", cached_session)
    b1, b2 = _mini_batch(fill=1), _mini_batch(fill=2)
    e1 = cache.insert_scan(k1, [b1])
    assert e1 is not None
    from spark_rapids_tpu.batch import Schema
    hit = cache.lookup_scan(k1, b1.schema)
    assert hit is not None
    entry, batches = hit  # entry pinned by this reader
    # inserting a second entry overflows the budget; the pinned entry
    # must SURVIVE (refs > 0), so the insert itself stays over budget
    cache.insert_scan(k2, [b2])
    assert not entry.dead or entry.handles, "pinned entry dropped"
    import jax.numpy as jnp
    assert int(jnp.sum(batches[0].columns[0].data)) == 256  # still live
    cache.release(entry)
    # now unpinned: the next budget sweep may drop it
    cache._lock.acquire()
    try:
        cache._evict_to_budget()
    finally:
        cache._lock.release()
    assert cache.bytes_cached() <= cache.max_bytes
    cache.clear()
    get_catalog().assert_no_leaks()


def test_invalidate_defers_close_to_last_release(cached_session, tmp_path):
    cache = QueryCache(max_bytes=1 << 20)
    k = _key_for(tmp_path, "c.parquet", cached_session)
    b = _mini_batch(fill=3)
    cache.insert_scan(k, [b])
    hit = cache.lookup_scan(k, b.schema)
    entry, batches = hit
    dropped = cache.invalidate_path(str(tmp_path))
    assert dropped == 1
    assert entry.dead and entry.handles, "close must wait for the reader"
    assert cache.lookup_scan(k, b.schema) is None, "dead entry served"
    import jax.numpy as jnp
    assert int(batches[0].columns[0].data[0]) == 3
    cache.release(entry)
    assert not entry.handles, "last release must close the handles"
    get_catalog().assert_no_leaks()


def test_spill_demotion_under_pressure(cached_session, tmp_path):
    """Cache entries register at PRIORITY_CACHE — under a shrunken device
    budget ensure_budget demotes THEM to host (live handles at higher
    priority stay), and a later hit transparently re-materializes."""
    s = cached_session
    pdf = _frame(4000, seed=9)
    path = _write_pq(tmp_path, "t.parquet", pdf)
    df = s.read_parquet(path)
    r1 = df.select("a", "b").collect()
    qc = get_query_cache()
    assert qc.entry_count() >= 1
    catalog = get_catalog()
    entry = next(iter(qc._entries.values()))
    assert all(h.priority == PRIORITY_CACHE for h in entry.handles)
    live = catalog.register(_mini_batch(fill=7), priority=1)
    old_budget = catalog.device_budget
    try:
        catalog.device_budget = live.device_bytes  # room for live only
        catalog.ensure_budget()
        assert all(h.state != SpillableBatch.DEVICE
                   for h in entry.handles), "cache must demote first"
        assert live.state == SpillableBatch.DEVICE, \
            "live query state demoted before the cache"
    finally:
        catalog.device_budget = old_budget
        live.close()
    # demoted != dropped: the next scan re-materializes and still hits
    r2 = df.select("a", "b").collect()
    assert r1 == r2
    assert qc.hits >= 1


def test_budget_eviction_emits_stats(cached_session, tmp_path):
    s = cached_session
    # one ~100KB entry fits, four do not: LRU entries must drop
    s.conf.set("spark.rapids.tpu.sql.cache.maxBytes", 1 << 17)
    QueryStats.reset()
    for i in range(4):
        p = _write_pq(tmp_path, f"t{i}.parquet", _frame(3000, seed=30 + i))
        s.read_parquet(p).select("a", "b", "k").collect()
    qc = get_query_cache()
    assert qc.bytes_cached() <= (1 << 17)
    assert QueryStats.get().cache_evictions > 0


def test_ttl_expiry(cached_session, tmp_path):
    s = cached_session
    s.conf.set("spark.rapids.tpu.sql.cache.ttlMs", 1)
    path = _write_pq(tmp_path, "t.parquet", _frame(500, seed=40))
    df = s.read_parquet(path)
    df.select("a").collect()
    time.sleep(0.01)
    qc = get_query_cache()
    h0 = qc.hits
    df.select("a").collect()  # expired: re-populates, no hit
    assert qc.hits == h0


def test_no_leaks_after_cache_drop(cached_session, tmp_path):
    s = cached_session
    path = _write_pq(tmp_path, "t.parquet", _frame(1000, seed=50))
    df = s.read_parquet(path)
    df.select("a", "b").collect()
    df.select("a").collect()
    qc = get_query_cache()
    assert qc.entry_count() > 0
    clear_query_cache()
    assert qc.entry_count() == 0
    get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------------
# concurrency: refcounted sharing through the scheduler
# ---------------------------------------------------------------------------------

def test_concurrent_queries_share_cache(cached_session, tmp_path):
    """N concurrent queries over the same table: results match the
    serial run, nothing leaks, and at least one query hit the cache
    (admission order decides how many — no use-after-evict either way)."""
    s = cached_session
    path = _write_pq(tmp_path, "t.parquet", _frame(6000, seed=60))
    df = s.read_parquet(path)

    def q():
        return df.filter(F.col("k") < 8).agg(
            F.sum(F.col("b")).alias("sb")).collect()

    serial = q()
    clear_query_cache()
    handles = [s.submit(q, label=f"cq{i}") for i in range(6)]
    results = [h.result(timeout=120) for h in handles]
    assert all(r == serial for r in results)
    qc = get_query_cache()
    assert qc.hits >= 1, "concurrent replay never hit"
    clear_query_cache()
    get_catalog().assert_no_leaks()

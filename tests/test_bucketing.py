"""Shape bucketing (plan/bucketing.py): ladder unit invariants (legacy
parity with the seed pow2 ladder, monotone geometric rungs, alignment,
string minimums), oracle-exact differentials at adjacent bucket
boundaries (exact fit / +1 row / bucket max / empty) over
project/filter/join/agg with nulls, and the full TPC-H suite vs the
pandas oracle under a dense geometric ladder."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu.batch as batch
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.plan import bucketing
from spark_rapids_tpu.plan.bucketing import BucketLadder
from spark_rapids_tpu.sql import functions as F

GEO = {"spark.rapids.tpu.warmstore.bucket.growth": 1.3,
       "spark.rapids.tpu.warmstore.bucket.align": 8}


@pytest.fixture(autouse=True)
def _restore_ladder():
    yield
    for k in GEO:
        TpuConf.unset_session(k)
    bucketing.reset_for_tests()


@pytest.fixture()
def geo_ladder():
    """Arm the dense geometric ladder the way a deployment would: via
    conf (ExecContext re-arms per query, so a direct install() would
    not survive the first query)."""
    for k, v in GEO.items():
        TpuConf.set_session(k, v)
    yield BucketLadder(GEO["spark.rapids.tpu.warmstore.bucket.growth"],
                       GEO["spark.rapids.tpu.warmstore.bucket.align"])


def _seed_capacity(n_rows, min_capacity=1024):
    """The seed engine's hard-coded pow2 ladder, verbatim."""
    cap = max(int(min_capacity), 1)
    n = max(int(n_rows), 1)
    while cap < n:
        cap <<= 1
    return cap


class TestLadder:
    def test_legacy_parity_randomized(self):
        """growth=2.0/align=1 must be byte-identical to the seed loop —
        the invariant that makes the default ladder safe to leave on."""
        lad = BucketLadder()
        assert lad.is_legacy()
        rng = np.random.default_rng(20260807)
        for _ in range(5000):
            n = int(rng.integers(0, 1 << 22))
            mc = int(rng.choice([1, 7, 128, 1024, 4096]))
            assert lad.capacity_for(n, mc) == _seed_capacity(n, mc), \
                (n, mc)

    def test_legacy_keeps_hook_disarmed(self):
        bucketing.install(BucketLadder())
        assert batch._ladder_hook is None
        bucketing.install(BucketLadder(1.3, 8))
        assert batch._ladder_hook is not None
        bucketing.reset_for_tests()
        assert batch._ladder_hook is None

    def test_rungs_monotone_and_covering(self):
        lad = BucketLadder(1.25, 1)
        prev = 0
        for n in range(1, 50_000, 997):
            cap = lad.capacity_for(n)
            assert cap >= n
            assert cap >= prev or n <= prev  # rungs never shrink
            # a rung is a fixed point: capacity_for(rung) == rung
            assert lad.capacity_for(cap) == cap
            prev = cap

    def test_align_rounds_every_rung(self):
        lad = BucketLadder(1.3, 128)
        for n in (1, 1000, 1025, 5000, 100_000):
            assert lad.capacity_for(n) % 128 == 0

    def test_min_rows_string_floor(self):
        lad = BucketLadder(1.3, 8, min_rows_string=4096)
        assert lad.capacity_for(10, has_strings=True) >= 4096
        assert lad.capacity_for(10, has_strings=False) < 4096

    def test_growth_clamps_and_terminates(self):
        lad = BucketLadder(0.5)  # nonsense growth clamps to 1.05
        assert lad.growth == 1.05
        assert lad.capacity_for(1_000_000) >= 1_000_000

    def test_signature_distinguishes_ladders(self):
        sigs = {BucketLadder().signature(),
                BucketLadder(1.3).signature(),
                BucketLadder(1.3, 8).signature(),
                BucketLadder(1.3, 8, 4096).signature()}
        assert len(sigs) == 4

    def test_configure_from_conf_and_rearm_free(self):
        conf = TpuConf(dict(GEO))
        bucketing.configure(conf)
        armed = bucketing.ladder()
        assert armed.growth == 1.3 and armed.align == 8
        bucketing.configure(conf)  # identical re-arm keeps the object
        assert bucketing.ladder() is armed

    def test_same_bucket_shares_capacity(self, geo_ladder):
        """Distinct cardinalities inside one rung pad to ONE capacity —
        the shape XLA keys the executable by (bench's
        programs_cold/programs_warm columns measure the same thing
        end-to-end)."""
        conf = TpuConf(dict(GEO))
        bucketing.configure(conf)
        c1 = batch.bucket_capacity(1500)
        c2 = batch.bucket_capacity(1600)
        assert c1 == c2


# ---------------------------------------------------------------------------
# Boundary differentials: geometric ladder vs the legacy ladder must be
# oracle-exact at the rungs where padding changes.
# ---------------------------------------------------------------------------

def _table(n):
    """Deterministic test table with nullable ints, floats, strings."""
    rng = np.random.default_rng(1000 + n)
    k = rng.integers(0, 23, n).astype("int64")
    v = (rng.random(n) * 100.0).round(6)
    q = rng.integers(-50, 50, n).astype("int32")
    null_mask = rng.random(n) < 0.15
    return pa.table({
        "k": pa.array(k),
        "q": pa.array(q, mask=null_mask),
        "v": pa.array(v),
        "s": pa.array([f"g{int(x) % 7}" for x in k]),
    })


def _run_pipeline(session, t, small):
    df = session.create_dataframe(t)
    dim = session.create_dataframe(small)
    out = (df.where(F.col("v") > F.lit(5.0))
             .join(dim, on="k", how="inner")
             .group_by("s")
             .agg(F.count_star().alias("n"),
                  F.sum(F.col("q")).alias("sq"),
                  F.sum(F.col("v") * F.col("w")).alias("sv"))
             .sort("s"))
    return out.collect()


def _rows_match(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert len(g) == len(w)
        for a, b in zip(g, w):
            if a is None or b is None:
                assert a is b, (g, w)  # null masks byte-identical
            elif isinstance(a, float):
                assert a == pytest.approx(b, rel=1e-12), (g, w)
            else:
                assert a == b, (g, w)


class TestBoundaryDifferential:
    @pytest.fixture(scope="class")
    def small(self):
        return pa.table({"k": np.arange(23, dtype="int64"),
                         "w": np.linspace(0.5, 2.0, 23)})

    def _boundaries(self):
        """Row counts straddling 3 adjacent geometric rungs: exact fit,
        one past (spills to the next rung), and rung max."""
        lad = BucketLadder(GEO["spark.rapids.tpu.warmstore.bucket.growth"],
                           GEO["spark.rapids.tpu.warmstore.bucket.align"])
        r1 = lad.capacity_for(1025)          # first rung past the floor
        r2 = lad.capacity_for(r1 + 1)
        r3 = lad.capacity_for(r2 + 1)
        assert r1 < r2 < r3
        return [r1, r1 + 1, r2, r2 + 1, r3]

    def test_boundary_rows_oracle_exact(self, session, small, geo_ladder):
        for n in self._boundaries():
            t = _table(n)
            for k in GEO:
                TpuConf.unset_session(k)
            want = _run_pipeline(session, t, small)  # legacy ladder
            for k, v in GEO.items():
                TpuConf.set_session(k, v)
            got = _run_pipeline(session, t, small)   # geometric ladder
            _rows_match(got, want)

    def test_empty_result_oracle_exact(self, session, small, geo_ladder):
        t = _table(1337)
        df = session.create_dataframe(t)
        out = (df.where(F.col("v") > F.lit(1e9))
                 .group_by("s").agg(F.count_star().alias("n"))
                 .collect())
        assert out == []


# ---------------------------------------------------------------------------
# The full TPC-H suite under the dense ladder: every query stays within
# oracle tolerance (padding is invisible behind the validity masks).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_db(session, tmp_path_factory):
    from spark_rapids_tpu.models import tpch_suite
    out = str(tmp_path_factory.mktemp("tpch_bucketed"))
    dfs = tpch_suite.load_db(session, 0.002, out)
    pds = tpch_suite.load_pdb(0.002, out)
    return dfs, pds


@pytest.mark.parametrize("name", [f"q{i}" for i in range(1, 23)])
def test_tpch_geometric_ladder_differential(tpch_db, name):
    from spark_rapids_tpu.models import tpch_suite
    dfs, pds = tpch_db
    for k, v in GEO.items():
        TpuConf.set_session(k, v)
    runner, oracle = tpch_suite.QUERIES[name]
    got = runner(dfs)
    want = oracle(pds)
    err = tpch_suite.rows_rel_err(got, want)
    assert err < 1e-6, f"{name}: rel_err={err} ({len(got)} rows)"

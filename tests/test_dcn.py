"""DCN process group: rendezvous, barriers, all-gather, heartbeats, peer
shuffle, and multi-process distributed aggregation.

Reference: the UCX shuffle transport + heartbeat registry
(shuffle-plugin/.../ucx/UCX.scala:71, RapidsShuffleHeartbeatManager.scala:50,
RapidsShuffleTransport.scala:22-80).  Multi-rank control-plane tests run the
real socket protocol with each rank on a thread; the end-to-end test spawns
real processes (each with its own JAX runtime) on localhost.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.parallel.dcn import (Coordinator, DcnShuffle,
                                           PeerFailedError, ProcessGroup,
                                           host_partition_ids)
from spark_rapids_tpu.sql import functions as F

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_group(world, **kw):
    """Spin up a coordinator + one ProcessGroup per rank (threads)."""
    coord = Coordinator(world, **kw.pop("coordinator_kw", {}))
    pgs = [None] * world
    errs = []

    def mk(r):
        try:
            pgs[r] = ProcessGroup(r, world, ("127.0.0.1", coord.port),
                                  coordinator=coord if r == 0 else None,
                                  **kw)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    assert all(pg is not None for pg in pgs)
    return coord, pgs


def _close_all(pgs):
    for pg in pgs:
        pg.close()


class TestControlPlane:
    def test_rendezvous_barrier_allgather(self):
        world = 3
        coord, pgs = _make_group(world)
        try:
            # every rank discovered every peer
            for pg in pgs:
                assert sorted(pg.peers) == [0, 1, 2]
            # barrier: all ranks must arrive before any is released
            order = []

            def go(pg):
                pg.barrier()
                order.append(pg.rank)

            ts = [threading.Thread(target=go, args=(pg,)) for pg in pgs]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert sorted(order) == [0, 1, 2]
            # allgather returns rank-ordered payloads everywhere
            outs = [None] * world

            def gather(pg):
                outs[pg.rank] = pg.all_gather_bytes(
                    f"payload-{pg.rank}".encode())

            ts = [threading.Thread(target=gather, args=(pg,)) for pg in pgs]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            expect = [f"payload-{r}".encode() for r in range(world)]
            for o in outs:
                assert o == expect
        finally:
            _close_all(pgs)

    def test_heartbeat_failure_detection(self):
        coord, pgs = _make_group(
            2, heartbeat_interval=0.1,
            coordinator_kw={"heartbeat_timeout": 0.5, "wait_timeout": 3.0})
        try:
            pgs[0].check_peers()  # both alive
            # rank 1 dies (stops heartbeating)
            pgs[1]._closed = True
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if 1 in pgs[0].dead_peers:
                    break
                time.sleep(0.1)
            assert 1 in pgs[0].dead_peers
            with pytest.raises(PeerFailedError, match=r"\[1\]"):
                pgs[0].check_peers()
            # a barrier nobody else joins surfaces the dead peer, not a hang
            with pytest.raises(PeerFailedError, match="barrier"):
                pgs[0].barrier()
        finally:
            _close_all(pgs)


class TestDcnShuffle:
    def test_peer_shuffle_roundtrip(self, tmp_path):
        world, n_parts = 2, 4
        coord, pgs = _make_group(world)
        try:
            shuffles = [DcnShuffle(pg, n_parts, str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            # each rank writes rows tagged with (rank, part)
            for rank, sh in enumerate(shuffles):
                for p in range(n_parts):
                    t = pa.table({"src": [rank] * 3,
                                  "part": [p] * 3,
                                  "v": list(range(3))})
                    sh.write_partition(p, t)
            ts = [threading.Thread(target=sh.commit) for sh in shuffles]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            # ownership covers all partitions exactly once
            owned = sorted(p for sh in shuffles for p in sh.my_parts())
            assert owned == list(range(n_parts))
            # each owner reads BOTH ranks' frames for its partitions
            for sh in shuffles:
                for p in sh.my_parts():
                    got = pa.concat_tables(sh.read_partition(p))
                    assert got.num_rows == 2 * 3
                    assert sorted(set(got.column("src").to_pylist())) == [0, 1]
                    assert set(got.column("part").to_pylist()) == {p}
            # close is collective (barriers so no rank tears down while a
            # peer still reads) — call it from all ranks concurrently
            ts = [threading.Thread(target=sh.close) for sh in shuffles]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
        finally:
            _close_all(pgs)


class TestHostPartitionIds:
    """Host murmur3 pids must match the device kernel bit-for-bit — ranks
    hash on host, the single-chip exchange hashes on device, and rows must
    land in the same partition either way."""

    @pytest.mark.parametrize("arrays,dtypes", [
        ({"a": [1, 2, 3, -7, 0, None]}, ["bigint"]),
        ({"a": np.array([1, -2, 3], np.int32)}, ["int"]),
        ({"a": [1.5, -0.0, 0.0, float("nan"), None]}, ["double"]),
        ({"a": [True, False, None]}, ["boolean"]),
        ({"a": [10, None, 30], "b": [1.5, 2.5, None]}, ["bigint", "double"]),
    ])
    def test_matches_device_hash(self, session, arrays, dtypes):
        import jax.numpy as jnp

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.batch import Field, Schema
        from spark_rapids_tpu.ops.hashing import spark_partition_id
        n_parts = 8
        table = pa.table(arrays)
        parse = {"bigint": T.INT64, "int": T.INT32, "double": T.FLOAT64,
                 "boolean": T.BOOLEAN}
        schema = Schema([Field(n, parse[d], True)
                         for n, d in zip(arrays, dtypes)])
        host = host_partition_ids(table, list(range(len(dtypes))), schema,
                                  n_parts)
        # device path
        keys = []
        for i, (name, dt) in enumerate(zip(arrays, dtypes)):
            col = table.column(i)
            valid = ~np.asarray(col.is_null())
            fill = False if dt == "boolean" else 0
            vals = np.asarray(col.fill_null(fill).to_numpy(
                zero_copy_only=False))
            data = jnp.asarray(vals.astype(parse[dt].numpy_dtype))
            keys.append((data, jnp.asarray(valid)))
        dev = np.asarray(spark_partition_id(keys, n_parts))
        np.testing.assert_array_equal(host, dev)

    def test_sliced_string_column_hashes_right_bytes(self, session):
        """A zero-copy table slice (offsets[0] > 0) must hash the same as
        an unsliced copy of the same strings."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.batch import Field, Schema
        schema = Schema([Field("s", T.STRING, True)])
        base = pa.table({"s": ["aa", "bb", "cc", "dd", "ee", "ff"]})
        sliced = base.slice(2, 3)
        fresh = pa.table({"s": ["cc", "dd", "ee"]})
        np.testing.assert_array_equal(
            host_partition_ids(sliced, [0], schema, 16),
            host_partition_ids(fresh, [0], schema, 16))

    def test_string_keys_hash_real_bytes(self, session):
        """Same strings on 'two ranks' (two dict orders) -> same pid."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.batch import Field, Schema
        schema = Schema([Field("s", T.STRING, True)])
        t1 = pa.table({"s": ["apple", "banana", None, "cherry", ""]})
        t2 = pa.table({"s": ["cherry", "", "banana", None, "apple"]})
        p1 = host_partition_ids(t1, [0], schema, 16)
        p2 = host_partition_ids(t2, [0], schema, 16)
        by_val1 = dict(zip(t1.column(0).to_pylist(), p1.tolist()))
        by_val2 = dict(zip(t2.column(0).to_pylist(), p2.tolist()))
        assert by_val1 == by_val2
        # null passes the seed through: pmod(42-ish seed path) is stable
        assert by_val1[None] == by_val2[None]


def _gen_shards(tmp_path, world, n=4000, seed=7):
    rng = np.random.default_rng(seed)
    tables = []
    for r in range(world):
        t = pa.table({
            "k": rng.integers(0, 37, n),
            "s": pa.array([["red", "green", "blue", None][i]
                           for i in rng.integers(0, 4, n)]),
            "v": rng.normal(size=n).round(3),
            "w": rng.normal(size=n).round(3),
        })
        pq.write_table(t, str(tmp_path / f"part-{r}.parquet"))
        tables.append(t)
    return pa.concat_tables(tables)


def _run_workers(tmp_path, world, query):
    port = _free_port()
    out = str(tmp_path / "result")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "dcn_worker.py"),
         "--rank", str(r), "--world", str(world), "--port", str(port),
         "--data", str(tmp_path), "--out", out, "--query", query],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(world)]
    logs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, lg in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{lg[-4000:]}"
    results = []
    for r in range(world):
        with open(f"{out}.{r}") as f:
            results.append(json.load(f))
    return results


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestDistributedAggEndToEnd:
    def test_grouped_agg_across_processes(self, tmp_path, session):
        world = 2
        whole = _gen_shards(tmp_path, world)
        results = _run_workers(tmp_path, world, "simple")
        # every rank returns the full, identical result
        assert results[0] == results[1]
        # oracle: the single-process engine over the concatenated data
        sess = srt.Session.get_or_create()
        df = sess.create_dataframe(whole)
        expect = (df.group_by("k", "s")
                  .agg(F.sum(F.col("v")).alias("sv"),
                       F.count_star().alias("c"),
                       F.avg(F.col("w")).alias("aw")).collect())

        def norm(rows):
            return sorted(
                ((k, s, round(float(sv), 6), c, round(float(aw), 6))
                 for k, s, sv, c, aw in rows),
                key=lambda r: (r[0], r[1] is None, str(r[1])))
        assert norm(results[0]) == norm(expect)

    def test_distributed_shuffled_join_across_processes(self, tmp_path,
                                                        session):
        """Both join sides sharded across ranks: cross-rank key matches
        require every exchange (join sides AND aggregate) to shuffle over
        DCN — a shard-local join would drop them."""
        world = 2
        whole = _gen_shards(tmp_path, world, n=1200, seed=23)
        # dim table sharded so that matching keys live on DIFFERENT ranks
        # than the fact rows (k % 2 vs round-robin): forces cross-rank flow
        dims = []
        for r in range(world):
            ks = [k for k in range(37) if k % world == r]
            t = pa.table({"dk": pa.array(ks, pa.int64()),
                          "dname": [f"name-{k:02d}" for k in ks]})
            pq.write_table(t, str(tmp_path / f"dim-{r}.parquet"))
            dims.append(t)
        results = _run_workers(tmp_path, world, "join")
        assert results[0] == results[1]
        sess = srt.Session.get_or_create()
        df = sess.create_dataframe(whole)
        dim = sess.create_dataframe(pa.concat_tables(dims))
        expect = (df.join(dim, on=[("k", "dk")])
                  .group_by("dname")
                  .agg(F.sum(F.col("v")).alias("sv"),
                       F.count_star().alias("c"))
                  .sort("dname").collect())
        got = [(n, round(float(sv), 6), c) for n, sv, c in results[0]]
        want = [(n, round(float(sv), 6), c) for n, sv, c in expect]
        assert got == want

    def test_broadcast_join_across_processes(self, tmp_path, session):
        """Broadcast join over DCN: the dim table is sharded so each rank
        holds only part of the build side — the broadcast exchange must
        all-gather it (GpuBroadcastExchangeExec.scala:352 analog) or
        cross-rank matches are lost."""
        world = 2
        whole = _gen_shards(tmp_path, world, n=1100, seed=31)
        dims = []
        for r in range(world):
            ks = [k for k in range(37) if k % world == r]
            t = pa.table({"dk": pa.array(ks, pa.int64()),
                          "dname": [f"name-{k:02d}" for k in ks]})
            pq.write_table(t, str(tmp_path / f"dim-{r}.parquet"))
            dims.append(t)
        results = _run_workers(tmp_path, world, "bjoin")
        assert results[0] == results[1]
        sess = srt.Session.get_or_create()
        df = sess.create_dataframe(whole)
        dim = sess.create_dataframe(pa.concat_tables(dims))
        expect = (df.join(dim, on=[("k", "dk")])
                  .group_by("dname")
                  .agg(F.sum(F.col("v")).alias("sv"),
                       F.count_star().alias("c"))
                  .sort("dname").collect())
        got = [(n, round(float(sv), 6), c) for n, sv, c in results[0]]
        want = [(n, round(float(sv), 6), c) for n, sv, c in expect]
        assert got == want

    def test_post_agg_sort_limit_replays_on_gathered(self, tmp_path,
                                                     session):
        world = 2
        whole = _gen_shards(tmp_path, world, n=1500, seed=11)
        results = _run_workers(tmp_path, world, "topk")
        sess = srt.Session.get_or_create()
        df = sess.create_dataframe(whole)
        expect = (df.group_by("k").agg(F.sum(F.col("v")).alias("sv"))
                  .sort(F.col("sv").desc()).limit(3).collect())
        got = [(k, round(float(sv), 6)) for k, sv in results[0]]
        want = [(k, round(float(sv), 6)) for k, sv in expect]
        assert got == want

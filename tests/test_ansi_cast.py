"""Cast matrix + ANSI mode (GpuCast.scala / CastOpSuite analog).

spark.rapids.tpu.sql.ansi.enabled=true makes overflowing casts, invalid
string casts, and division by zero RAISE (ArithmeticError) instead of
wrapping/clamping/nulling — via a traced per-row error channel reduced at
each stage boundary (exprs.EvalContext.errors)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F

ANSI = "spark.rapids.tpu.sql.ansi.enabled"


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def _cast(df, colname, dt):
    from spark_rapids_tpu import exprs as E
    from spark_rapids_tpu.sql.column import Column
    return df.select(Column(E.Cast(E.UnresolvedColumn(colname), dt))
                     .alias("c"))


class TestLegacyCasts:
    def test_int_narrowing_wraps(self, sess):
        df = sess.create_dataframe(pa.table({"x": pa.array(
            [300, -300, 40], type=pa.int64())}))
        rows = _cast(df, "x", T.INT8).collect()
        assert [r[0] for r in rows] == [44, -44, 40]  # 300 % 256 etc

    def test_float_to_int_clamps_nan_zero(self, sess):
        df = sess.create_dataframe(pa.table({"x": pa.array(
            [1.9, -1.9, float("nan"), float("inf"), -float("inf")])}))
        rows = _cast(df, "x", T.INT32).collect()
        assert rows[0][0] == 1 and rows[1][0] == -1
        assert rows[2][0] == 0
        assert rows[3][0] == 2**31 - 1 and rows[4][0] == -(2**31)

    def test_divide_by_zero_nulls(self, sess):
        df = sess.create_dataframe(pa.table({"a": [1.0, 2.0],
                                             "b": [0.0, 2.0]}))
        rows = df.select((F.col("a") / F.col("b")).alias("d")).collect()
        assert rows[0][0] is None and rows[1][0] == 1.0

    def test_string_to_int_invalid_nulls(self, sess):
        df = sess.create_dataframe(pa.table({"s": ["12", "x", "7"]}))
        rows = _cast(df, "s", T.INT32).collect()
        assert [r[0] for r in rows] == [12, None, 7]

    def test_int_to_decimal_and_rescale(self, sess):
        df = sess.create_dataframe(pa.table({"x": pa.array(
            [3, 12], type=pa.int64())}))
        rows = _cast(df, "x", T.decimal(6, 2)).collect()
        assert [float(r[0]) for r in rows] == [3.0, 12.0]


class TestAnsiCasts:
    def test_ansi_narrowing_overflow_raises(self, sess):
        sess.conf.set(ANSI, True)
        try:
            df = sess.create_dataframe(pa.table({"x": pa.array(
                [300], type=pa.int64())}))
            with pytest.raises(ArithmeticError, match="ANSI"):
                _cast(df, "x", T.INT8).collect()
        finally:
            sess.conf.set(ANSI, False)

    def test_ansi_float_to_int_nan_raises(self, sess):
        sess.conf.set(ANSI, True)
        try:
            df = sess.create_dataframe(pa.table({"x": [float("nan")]}))
            with pytest.raises(ArithmeticError, match="ANSI"):
                _cast(df, "x", T.INT64).collect()
        finally:
            sess.conf.set(ANSI, False)

    def test_ansi_divide_by_zero_raises(self, sess):
        sess.conf.set(ANSI, True)
        try:
            df = sess.create_dataframe(pa.table({"a": [1.0], "b": [0.0]}))
            with pytest.raises(ArithmeticError, match="ANSI"):
                df.select((F.col("a") / F.col("b")).alias("d")).collect()
        finally:
            sess.conf.set(ANSI, False)

    def test_ansi_valid_casts_pass(self, sess):
        sess.conf.set(ANSI, True)
        try:
            df = sess.create_dataframe(pa.table({"x": pa.array(
                [10, -10], type=pa.int64())}))
            rows = _cast(df, "x", T.INT8).collect()
            assert [r[0] for r in rows] == [10, -10]
            df2 = sess.create_dataframe(pa.table({"a": [4.0], "b": [2.0]}))
            r2 = df2.select((F.col("a") / F.col("b")).alias("d")).collect()
            assert r2[0][0] == 2.0
        finally:
            sess.conf.set(ANSI, False)

    def test_ansi_invalid_string_cast_raises_cpu_path(self, sess):
        sess.conf.set(ANSI, True)
        try:
            df = sess.create_dataframe(pa.table({"s": ["12", "oops"]}))
            with pytest.raises(ArithmeticError, match="ANSI"):
                _cast(df, "s", T.INT32).collect()
        finally:
            sess.conf.set(ANSI, False)

    def test_ansi_rows_filtered_out_do_not_raise(self, sess):
        """An overflowing row removed by an EARLIER filter step in the
        same stage must not raise (the error mask is confined to live
        rows)."""
        sess.conf.set(ANSI, True)
        try:
            t = pa.table({"x": pa.array([300, 5], type=pa.int64())})
            df = sess.create_dataframe(t).filter(F.col("x") < 100)
            rows = _cast(df, "x", T.INT8).collect()
            assert [r[0] for r in rows] == [5]
        finally:
            sess.conf.set(ANSI, False)

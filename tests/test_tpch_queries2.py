"""TPC-H acceptance suite part 2: the ten queries not covered by
test_tpch_queries.py (Q2, Q8, Q11, Q13, Q15, Q16, Q17, Q20, Q21, Q22),
expressed in DataFrame form with manual decorrelation (scalar subqueries
become collected literals; EXISTS/NOT EXISTS become semi/anti joins — the
same rewrites Spark's optimizer performs before the reference plugin sees
the plan).  Oracles are pandas over the same seeded mini database.
"""

import datetime

import numpy as np
import pandas as pd
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


@pytest.fixture(scope="module")
def db(session):
    from spark_rapids_tpu.models.tpch import gen_tables
    tables = gen_tables()
    dfs = {k: session.create_dataframe(t) for k, t in tables.items()}
    pds = {k: t.to_pandas() for k, t in tables.items()}
    return dfs, pds


def _close(got, exp, places=6):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, e in zip(got, exp):
        for a, b in zip(g, e):
            if isinstance(b, float) and not isinstance(b, bool):
                assert a == pytest.approx(b, rel=10 ** -places), (g, e)
            else:
                assert a == b, (g, e)


def test_q2_minimum_cost_supplier(db):
    f = F()
    dfs, pds = db
    europe_sup = (dfs["supplier"]
                  .join(dfs["nation"], on=[("s_nationkey", "n_nationkey")])
                  .join(dfs["region"].filter(f.col("r_name") == "EUROPE"),
                        on=[("n_regionkey", "r_regionkey")]))
    ps_eu = dfs["partsupp"].join(
        europe_sup, on=[("ps_suppkey", "s_suppkey")])
    min_cost = (ps_eu.group_by("ps_partkey")
                .agg(f.min(f.col("ps_supplycost")).alias("min_cost")))
    q = (ps_eu.join(min_cost, on=["ps_partkey"])
         .filter(f.col("ps_supplycost") == f.col("min_cost"))
         .join(dfs["part"].filter(f.col("p_size") == 15),
               on=[("ps_partkey", "p_partkey")])
         .select("s_acctbal", "s_name", "n_name", "ps_partkey",
                 "ps_supplycost")
         .sort(f.col("s_acctbal").desc(), "s_name"))
    got = q.collect()

    s, n, r, ps, p = (pds[k] for k in
                      ["supplier", "nation", "region", "partsupp", "part"])
    eu = (s.merge(n, left_on="s_nationkey", right_on="n_nationkey")
          .merge(r[r.r_name == "EUROPE"], left_on="n_regionkey",
                 right_on="r_regionkey"))
    pe = ps.merge(eu, left_on="ps_suppkey", right_on="s_suppkey")
    mc = pe.groupby("ps_partkey")["ps_supplycost"].min().rename("min_cost")
    m = pe.merge(mc, on="ps_partkey")
    m = m[m.ps_supplycost == m.min_cost].merge(
        p[p.p_size == 15], left_on="ps_partkey", right_on="p_partkey")
    exp = m.sort_values(["s_acctbal", "s_name"],
                        ascending=[False, True])
    _close(got, list(zip(exp.s_acctbal, exp.s_name, exp.n_name,
                         exp.ps_partkey, exp.ps_supplycost)))


def test_q8_national_market_share(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1995, 1, 1), datetime.date(1996, 12, 31)
    n2 = dfs["nation"].select(
        f.col("n_nationkey").alias("n2_key"),
        f.col("n_name").alias("n2_name"),
        f.col("n_regionkey").alias("n2_region"))
    q = (dfs["lineitem"]
         .join(dfs["part"], on=[("l_partkey", "p_partkey")])
         .join(dfs["supplier"], on=[("l_suppkey", "s_suppkey")])
         .join(dfs["orders"], on=[("l_orderkey", "o_orderkey")])
         .filter((f.col("o_orderdate") >= lo) & (f.col("o_orderdate") <= hi))
         .join(dfs["customer"], on=[("o_custkey", "c_custkey")])
         .join(dfs["nation"], on=[("c_nationkey", "n_nationkey")])
         .join(dfs["region"].filter(f.col("r_name") == "AMERICA"),
               on=[("n_regionkey", "r_regionkey")])
         .join(n2, on=[("s_nationkey", "n2_key")])
         .with_column("o_year", f.year(f.col("o_orderdate")))
         .with_column("volume",
                      f.col("l_extendedprice") * (1 - f.col("l_discount")))
         .with_column("brazil_volume",
                      f.when(f.col("n2_name") == "BRAZIL",
                             f.col("volume")).otherwise(f.lit(0.0)))
         .group_by("o_year")
         .agg(f.sum(f.col("brazil_volume")).alias("bv"),
              f.sum(f.col("volume")).alias("tv"))
         .select("o_year", (f.col("bv") / f.col("tv")).alias("mkt_share"))
         .sort("o_year"))
    got = q.collect()

    l, p, s, o, c, n, r = (pds[k] for k in
                           ["lineitem", "part", "supplier", "orders",
                            "customer", "nation", "region"])
    m = (l.merge(p, left_on="l_partkey", right_on="p_partkey")
         .merge(s, left_on="l_suppkey", right_on="s_suppkey")
         .merge(o, left_on="l_orderkey", right_on="o_orderkey"))
    m = m[(m.o_orderdate >= lo) & (m.o_orderdate <= hi)]
    m = (m.merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(n, left_on="c_nationkey", right_on="n_nationkey"))
    m = m.merge(r[r.r_name == "AMERICA"], left_on="n_regionkey",
                right_on="r_regionkey")
    n2p = n.rename(columns={"n_nationkey": "n2_key", "n_name": "n2_name"})
    m = m.merge(n2p[["n2_key", "n2_name"]], left_on="s_nationkey",
                right_on="n2_key")
    m["o_year"] = pd.to_datetime(m.o_orderdate).dt.year
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    m["bv"] = np.where(m.n2_name == "BRAZIL", m.volume, 0.0)
    g = m.groupby("o_year").agg(bv=("bv", "sum"), tv=("volume", "sum"))
    g["share"] = g.bv / g.tv
    exp = g.reset_index().sort_values("o_year")
    _close(got, list(zip(exp.o_year, exp.share)))


def test_q11_important_stock(db):
    f = F()
    dfs, pds = db
    nat = "GERMANY"
    ps_n = (dfs["partsupp"]
            .join(dfs["supplier"], on=[("ps_suppkey", "s_suppkey")])
            .join(dfs["nation"].filter(f.col("n_name") == nat),
                  on=[("s_nationkey", "n_nationkey")])
            .with_column("value",
                         f.col("ps_supplycost") * f.col("ps_availqty")))
    total = ps_n.agg(f.sum(f.col("value")).alias("t")).collect()[0][0]
    threshold = total * 0.01
    q = (ps_n.group_by("ps_partkey")
         .agg(f.sum(f.col("value")).alias("value"))
         .filter(f.col("value") > f.lit(threshold))
         .sort(f.col("value").desc()))
    got = q.collect()

    ps, s, n = (pds[k] for k in ["partsupp", "supplier", "nation"])
    m = (ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey")
         .merge(n[n.n_name == nat], left_on="s_nationkey",
                right_on="n_nationkey"))
    m["value"] = m.ps_supplycost * m.ps_availqty
    tot = m.value.sum()
    g = m.groupby("ps_partkey")["value"].sum().reset_index()
    exp = g[g.value > tot * 0.01].sort_values("value", ascending=False)
    _close(got, list(zip(exp.ps_partkey, exp.value)))


def test_q13_customer_distribution(db):
    f = F()
    dfs, pds = db
    # minidb has no o_comment; the excluded-orders predicate becomes a
    # priority filter (same LEFT-join-then-count shape)
    kept = dfs["orders"].filter(f.col("o_orderpriority") != "1-URGENT")
    per_cust = (dfs["customer"]
                .join(kept, on=[("c_custkey", "o_custkey")], how="left")
                .group_by("c_custkey")
                .agg(f.count(f.col("o_orderkey")).alias("c_count")))
    q = (per_cust.group_by("c_count")
         .agg(f.count_star().alias("custdist"))
         .sort(f.col("custdist").desc(), f.col("c_count").desc()))
    got = q.collect()

    c, o = pds["customer"], pds["orders"]
    ko = o[o.o_orderpriority != "1-URGENT"]
    m = c.merge(ko, left_on="c_custkey", right_on="o_custkey", how="left")
    cc = m.groupby("c_custkey")["o_orderkey"].count().reset_index(
        name="c_count")
    exp = (cc.groupby("c_count").size().reset_index(name="custdist")
           .sort_values(["custdist", "c_count"], ascending=[False, False]))
    _close(got, list(zip(exp.c_count, exp.custdist)))


def test_q15_top_supplier(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1996, 1, 1), datetime.date(1996, 4, 1)
    revenue = (dfs["lineitem"]
               .filter((f.col("l_shipdate") >= lo)
                       & (f.col("l_shipdate") < hi))
               .with_column("rev", f.col("l_extendedprice")
                            * (1 - f.col("l_discount")))
               .group_by("l_suppkey")
               .agg(f.sum(f.col("rev")).alias("total_revenue")))
    top = revenue.agg(f.max(f.col("total_revenue")).alias("m")) \
        .collect()[0][0]
    q = (dfs["supplier"]
         .join(revenue.filter(f.col("total_revenue") == f.lit(top)),
               on=[("s_suppkey", "l_suppkey")])
         .select("s_suppkey", "s_name", "total_revenue")
         .sort("s_suppkey"))
    got = q.collect()

    l, s = pds["lineitem"], pds["supplier"]
    lf = l[(l.l_shipdate >= lo) & (l.l_shipdate < hi)].copy()
    lf["rev"] = lf.l_extendedprice * (1 - lf.l_discount)
    g = lf.groupby("l_suppkey")["rev"].sum()
    mx = g.max()
    winners = g[g == mx].reset_index()
    exp = (s.merge(winners, left_on="s_suppkey", right_on="l_suppkey")
           .sort_values("s_suppkey"))
    _close(got, list(zip(exp.s_suppkey, exp.s_name, exp.rev)))


def test_q16_parts_supplier_relationship(db):
    f = F()
    dfs, pds = db
    # excluded suppliers (TPC-H: comment LIKE customer complaints):
    # minidb substitute = negative account balance
    bad = dfs["supplier"].filter(f.col("s_acctbal") < 0)
    q = (dfs["partsupp"]
         .join(bad, on=[("ps_suppkey", "s_suppkey")], how="anti")
         .join(dfs["part"].filter((f.col("p_brand") != "Brand#45")
                                  & (f.col("p_size").isin(1, 4, 7, 10,
                                                          14, 23))),
               on=[("ps_partkey", "p_partkey")])
         .select("p_brand", "p_type", "p_size", "ps_suppkey").distinct()
         .group_by("p_brand", "p_type", "p_size")
         .agg(f.count_star().alias("supplier_cnt"))
         .sort(f.col("supplier_cnt").desc(), "p_brand", "p_type",
               "p_size"))
    got = q.collect()

    ps, s, p = pds["partsupp"], pds["supplier"], pds["part"]
    badk = set(s.loc[s.s_acctbal < 0, "s_suppkey"])
    m = ps[~ps.ps_suppkey.isin(badk)].merge(
        p[(p.p_brand != "Brand#45")
          & p.p_size.isin([1, 4, 7, 10, 14, 23])],
        left_on="ps_partkey", right_on="p_partkey")
    d = m[["p_brand", "p_type", "p_size", "ps_suppkey"]].drop_duplicates()
    exp = (d.groupby(["p_brand", "p_type", "p_size"]).size()
           .reset_index(name="cnt")
           .sort_values(["cnt", "p_brand", "p_type", "p_size"],
                        ascending=[False, True, True, True]))
    _close(got, list(zip(exp.p_brand, exp.p_type, exp.p_size, exp.cnt)))


def test_q17_small_quantity_order(db):
    f = F()
    dfs, pds = db
    parts = dfs["part"].filter(f.col("p_container") == "JUMBO PKG")
    avg_qty = (dfs["lineitem"].group_by("l_partkey")
               .agg(f.avg(f.col("l_quantity")).alias("aq"))
               .select(f.col("l_partkey").alias("ak"),
                       (f.col("aq") * 0.2).alias("lim")))
    q = (dfs["lineitem"]
         .join(parts, on=[("l_partkey", "p_partkey")])
         .join(avg_qty, on=[("l_partkey", "ak")])
         .filter(f.col("l_quantity") < f.col("lim"))
         .agg(f.sum(f.col("l_extendedprice")).alias("s"))
         .select((f.col("s") / 7.0).alias("avg_yearly")))
    got = q.collect()

    l, p = pds["lineitem"], pds["part"]
    lim = (l.groupby("l_partkey")["l_quantity"].mean() * 0.2).rename("lim")
    m = (l.merge(p[p.p_container == "JUMBO PKG"], left_on="l_partkey",
                 right_on="p_partkey").merge(lim, on="l_partkey"))
    m = m[m.l_quantity < m.lim]
    expect = m.l_extendedprice.sum() / 7.0 if len(m) else None
    if expect is None:
        assert got[0][0] is None
    else:
        assert got[0][0] == pytest.approx(expect, rel=1e-9)


def test_q20_potential_part_promotion(db):
    f = F()
    dfs, pds = db
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    shipped = (dfs["lineitem"]
               .filter((f.col("l_shipdate") >= lo)
                       & (f.col("l_shipdate") < hi))
               .group_by("l_partkey", "l_suppkey")
               .agg(f.sum(f.col("l_quantity")).alias("sq"))
               .with_column("half_qty", f.col("sq") * 0.5))
    forest = dfs["part"].filter(f.like(f.col("p_name"), "part 1%"))
    excess = (dfs["partsupp"]
              .join(forest, on=[("ps_partkey", "p_partkey")], how="semi")
              .join(shipped.select(f.col("l_partkey").alias("pk"),
                                   f.col("l_suppkey").alias("sk"),
                                   "half_qty"),
                    on=[("ps_partkey", "pk"), ("ps_suppkey", "sk")])
              .filter(f.col("ps_availqty") > f.col("half_qty")))
    q = (dfs["supplier"]
         .join(excess, on=[("s_suppkey", "ps_suppkey")], how="semi")
         .join(dfs["nation"].filter(f.col("n_name") == "CANADA"),
               on=[("s_nationkey", "n_nationkey")])
         .select("s_name", "s_suppkey").sort("s_name"))
    got = q.collect()

    l, p, ps, s, n = (pds[k] for k in
                      ["lineitem", "part", "partsupp", "supplier",
                       "nation"])
    lf = l[(l.l_shipdate >= lo) & (l.l_shipdate < hi)]
    g = (lf.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum() * 0.5
         ).rename("half_qty").reset_index()
    fk = set(p.loc[p.p_name.str.startswith("part 1"), "p_partkey"])
    m = ps[ps.ps_partkey.isin(fk)].merge(
        g, left_on=["ps_partkey", "ps_suppkey"],
        right_on=["l_partkey", "l_suppkey"])
    keys = set(m.loc[m.ps_availqty > m.half_qty, "ps_suppkey"])
    exp = (s[s.s_suppkey.isin(keys)]
           .merge(n[n.n_name == "CANADA"], left_on="s_nationkey",
                  right_on="n_nationkey").sort_values("s_name"))
    _close(got, list(zip(exp.s_name, exp.s_suppkey)))


def test_q21_suppliers_who_kept_orders_waiting(db):
    f = F()
    dfs, pds = db
    late = (dfs["lineitem"]
            .filter(f.col("l_receiptdate") > f.col("l_commitdate"))
            .select(f.col("l_orderkey").alias("late_ok"),
                    f.col("l_suppkey").alias("late_sk")))
    # orders with >1 distinct supplier (multi-supplier orders)
    multi = (dfs["lineitem"].select("l_orderkey", "l_suppkey").distinct()
             .group_by("l_orderkey")
             .agg(f.count_star().alias("n_sups"))
             .filter(f.col("n_sups") > 1)
             .select(f.col("l_orderkey").alias("mk")))
    # orders where >1 distinct supplier was late
    multi_late = (late.distinct().group_by("late_ok")
                  .agg(f.count_star().alias("n_late"))
                  .filter(f.col("n_late") > 1)
                  .select(f.col("late_ok").alias("xk")))
    q = (late.distinct()
         .join(dfs["orders"].filter(f.col("o_orderstatus") == "F"),
               on=[("late_ok", "o_orderkey")], how="semi")
         .join(multi, on=[("late_ok", "mk")], how="semi")
         .join(multi_late, on=[("late_ok", "xk")], how="anti")
         .join(dfs["supplier"], on=[("late_sk", "s_suppkey")])
         .group_by("s_name")
         .agg(f.count_star().alias("numwait"))
         .sort(f.col("numwait").desc(), "s_name"))
    got = q.collect()

    l, o, s = pds["lineitem"], pds["orders"], pds["supplier"]
    latep = l[l.l_receiptdate > l.l_commitdate][
        ["l_orderkey", "l_suppkey"]].drop_duplicates()
    f_orders = set(o.loc[o.o_orderstatus == "F", "o_orderkey"])
    n_sup = l[["l_orderkey", "l_suppkey"]].drop_duplicates() \
        .groupby("l_orderkey").size()
    multi_ok = set(n_sup[n_sup > 1].index)
    n_late = latep.groupby("l_orderkey").size()
    multi_late_ok = set(n_late[n_late > 1].index)
    m = latep[latep.l_orderkey.isin(f_orders)
              & latep.l_orderkey.isin(multi_ok)
              & ~latep.l_orderkey.isin(multi_late_ok)]
    m = m.merge(s, left_on="l_suppkey", right_on="s_suppkey")
    exp = (m.groupby("s_name").size().reset_index(name="numwait")
           .sort_values(["numwait", "s_name"], ascending=[False, True]))
    _close(got, list(zip(exp.s_name, exp.numwait)))


def test_q22_global_sales_opportunity(db):
    f = F()
    dfs, pds = db
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = dfs["customer"].with_column(
        "cntrycode", f.substring(f.col("c_phone"), 1, 2))
    in_codes = cust.filter(f.col("cntrycode").isin(*codes))
    avg_bal = in_codes.filter(f.col("c_acctbal") > 0.0) \
        .agg(f.avg(f.col("c_acctbal")).alias("a")).collect()[0][0]
    q = (in_codes.filter(f.col("c_acctbal") > f.lit(avg_bal))
         .join(dfs["orders"], on=[("c_custkey", "o_custkey")], how="anti")
         .group_by("cntrycode")
         .agg(f.count_star().alias("numcust"),
              f.sum(f.col("c_acctbal")).alias("totacctbal"))
         .sort("cntrycode"))
    got = q.collect()

    c, o = pds["customer"], pds["orders"]
    cc = c.copy()
    cc["cntrycode"] = cc.c_phone.str[:2]
    ic = cc[cc.cntrycode.isin(codes)]
    ab = ic.loc[ic.c_acctbal > 0, "c_acctbal"].mean()
    has_orders = set(o.o_custkey)
    m = ic[(ic.c_acctbal > ab) & ~ic.c_custkey.isin(has_orders)]
    exp = (m.groupby("cntrycode")
           .agg(numcust=("c_custkey", "size"),
                totacctbal=("c_acctbal", "sum"))
           .reset_index().sort_values("cntrycode"))
    _close(got, list(zip(exp.cntrycode, exp.numcust, exp.totacctbal)))

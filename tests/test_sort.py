"""Sort tests (sort_test.py analog): direction, null placement, NaN order,
multi-key, stability across batches."""

import numpy as np
import pandas as pd
import pytest

from .support import DoubleGen, IntGen, assert_rows_equal, gen_table, pdf_rows


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_sort_asc_desc_nulls(session):
    f = F()
    df = session.create_dataframe(
        {"a": pd.array([3, None, 1, 2, None], dtype="Int64"),
         "v": [10, 20, 30, 40, 50]})
    out = df.sort(f.col("a").asc()).collect()
    assert [r[0] for r in out] == [None, None, 1, 2, 3]  # nulls first (ASC)
    out = df.sort(f.col("a").desc()).collect()
    assert [r[0] for r in out] == [3, 2, 1, None, None]  # nulls last (DESC)
    out = df.sort(f.col("a").asc_nulls_last()).collect()
    assert [r[0] for r in out] == [1, 2, 3, None, None]


def test_sort_nan_greatest(session):
    f = F()
    nan = float("nan")
    df = session.create_dataframe({"x": [1.0, nan, -1.0, float("inf")]})
    out = [r[0] for r in df.sort(f.col("x").asc()).collect()]
    assert out[0] == -1.0 and out[1] == 1.0 and out[2] == float("inf")
    assert np.isnan(out[3])  # NaN sorts greater than +inf (Spark)


def test_sort_multi_key_random(session, rng):
    table, pdf = gen_table(rng, {"a": IntGen(lo=0, hi=5),
                                 "b": DoubleGen(special=False),
                                 "c": IntGen(nullable=False)}, 300)
    f = F()
    df = session.create_dataframe(table)
    out = df.sort(f.col("a").asc(), f.col("b").desc()).collect()
    exp = pdf.sort_values(["a", "b"], ascending=[True, False],
                          na_position="first")
    # pandas puts NaN/None differently per key; compare only key columns order
    exp_a = [None if pd.isna(x) else int(x) for x in exp.a]
    assert [r[0] for r in out] == exp_a


def test_sort_desc_int64_extremes(session):
    f = F()
    big = 2 ** 62
    df = session.create_dataframe({"a": [0, -big, big, 1]})
    out = [r[0] for r in df.sort(f.col("a").desc()).collect()]
    assert out == [big, 1, 0, -big]

"""Host-staged multithreaded shuffle (RapidsShuffleThreadedWriter/Reader
analog): frame files, compression, and query equivalence vs CACHE_ONLY."""

import os

import numpy as np
import pyarrow as pa
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_frame_roundtrip(tmp_path):
    from spark_rapids_tpu.parallel.host_shuffle import HostShuffle
    sh = HostShuffle(3, str(tmp_path), num_threads=2, compress=True)
    try:
        t1 = pa.table({"x": pa.array([1, 2, 3], type=pa.int64())})
        t2 = pa.table({"x": pa.array([4], type=pa.int64())})
        sh.write_partition(0, t1)
        sh.write_partition(2, t2)
        sh.write_partition(0, t2)
        sh.finish_writes()
        p0 = list(sh.read_partition(0))
        assert sum(t.num_rows for t in p0) == 4
        assert list(sh.read_partition(1)) == []
        assert [t.num_rows for t in sh.read_partition(2)] == [1]
    finally:
        sh.close()
    assert not os.path.exists(sh.dir)


@pytest.mark.parametrize("mode", ["HOST", "CACHE_ONLY"])
def test_grouped_agg_same_result_both_modes(session, rng, mode):
    from .support import DoubleGen, IntGen, gen_table
    f = F()
    table, pdf = gen_table(rng, {
        "k": IntGen(lo=0, hi=50, dtype="int64", nullable=True),
        "v": DoubleGen(special=False, nullable=False)}, 2000)
    session.conf.set("spark.rapids.tpu.shuffle.mode", mode)
    try:
        df = session.create_dataframe(table)
        got = dict(df.group_by("k").agg(
            f.sum(f.col("v")).alias("s")).collect())
    finally:
        session.conf.unset("spark.rapids.tpu.shuffle.mode")
    import pandas as pd
    exp = pdf.groupby("k", dropna=False)["v"].sum()
    assert len(got) == len(exp)
    for k, v in exp.items():
        key = None if pd.isna(k) else int(k)
        assert got[key] == pytest.approx(v)


def test_join_through_host_shuffle(session):
    f = F()
    session.conf.set("spark.rapids.tpu.shuffle.mode", "HOST")
    try:
        a = session.create_dataframe(
            {"k": list(range(100)), "x": [float(i) for i in range(100)]})
        b = session.create_dataframe(
            {"k": [i for i in range(0, 100, 2)],
             "y": [float(i * 10) for i in range(0, 100, 2)]})
        got = sorted(a.join(b, on=["k"]).select("k", "x", "y").collect())
        assert len(got) == 50
        assert got[0] == (0, 0.0, 0.0) and got[-1] == (98, 98.0, 980.0)
    finally:
        session.conf.unset("spark.rapids.tpu.shuffle.mode")


def test_host_shuffle_with_string_values(session):
    f = F()
    session.conf.set("spark.rapids.tpu.shuffle.mode", "HOST")
    try:
        df = session.create_dataframe(
            {"k": [1, 2, 1, 3], "s": ["a", "b", None, "c"]})
        got = sorted(df.group_by("k").agg(
            f.count(f.col("s")).alias("n")).collect())
        assert got == [(1, 1), (2, 1), (3, 1)]
    finally:
        session.conf.unset("spark.rapids.tpu.shuffle.mode")

"""Distributed failure survival (ISSUE 6): epoch-fenced membership,
cross-peer fragment recovery from durable map output, dead-peer
fast-fail, coordinator-loss detection, and scheduler resubmission.

The multi-process killed-peer chaos differential (@slow) kills a real
rank mid-shuffle (``dcn.peer_kill``, silent and hard modes) and asserts
the survivors' result is identical to the fault-free run; the tier-1
single-process simulation drives the same recovery machinery —
declaration, durable re-pull, orphan adoption — over thread ranks.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import ALL_ENTRIES, TpuConf
from spark_rapids_tpu.faults import (INJECTOR, PermanentFault, QueryFaulted,
                                     TransientFault, budget_scope,
                                     transient_retry)
from spark_rapids_tpu.memory.spill import get_catalog
from spark_rapids_tpu.parallel.dcn import (Coordinator, CoordinatorLostError,
                                           CoordinatorUnrecoverableError,
                                           DcnShuffle, PeerFailedError,
                                           PeerLostError, ProcessGroup)
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.utils.metrics import QueryStats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST = {
    "spark.rapids.tpu.faults.backoff.baseMs": 1.0,
    "spark.rapids.tpu.faults.backoff.maxMs": 10.0,
}


@pytest.fixture()
def fast_backoff():
    for k, v in FAST.items():
        TpuConf.set_session(k, v)
    yield
    for k in FAST:
        TpuConf.unset_session(k)
    INJECTOR.arm()


def _make_group(world, hb_timeout=0.5, wait_timeout=8.0, interval=0.1):
    coord = Coordinator(world, heartbeat_timeout=hb_timeout,
                        wait_timeout=wait_timeout)
    pgs = [None] * world
    errs = []

    def mk(r):
        try:
            pgs[r] = ProcessGroup(r, world, ("127.0.0.1", coord.port),
                                  coordinator=coord if r == 0 else None,
                                  heartbeat_interval=interval)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    return coord, pgs


def _silently_kill(pg):
    """Thread-rank analog of a silent peer death: heartbeats stop and
    the peer server freezes (open socket, no answers)."""
    pg._closed = True
    pg._server.freeze()


def _wait_declared(observer, rank, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if rank in observer.dead_peers:
            return
        time.sleep(0.05)
    raise AssertionError(
        f"rank {rank} never declared dead (dead={observer.dead_peers})")


# ---------------------------------------------------------------------------
# Epoch-fenced membership.
# ---------------------------------------------------------------------------

class TestEpochFencing:
    def test_declared_death_bumps_epoch(self, fast_backoff):
        coord, pgs = _make_group(2)
        try:
            assert coord.epoch == 0
            _silently_kill(pgs[1])
            _wait_declared(pgs[0], 1)
            assert coord.epoch >= 1
            assert coord.declared_dead() == [1]
            # survivors absorbed the bumped epoch through heartbeats
            deadline = time.monotonic() + 5
            while pgs[0].epoch < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert pgs[0].epoch >= 1
        finally:
            for pg in pgs:
                pg.close()

    def test_stale_epoch_collective_resyncs_transparently(self,
                                                          fast_backoff):
        """A live rank whose epoch lags a membership change is rejected
        with stale_epoch and resyncs on the retry — collectives carry
        the epoch without wedging survivors."""
        coord, pgs = _make_group(3)
        try:
            _silently_kill(pgs[2])
            _wait_declared(pgs[0], 2)
            # force rank 1's view stale (as if it had not heartbeated
            # since the bump), then run a collective: the coordinator
            # rejects the stale frame, the reply resyncs, retry joins
            pgs[1].epoch = 0
            pgs[1]._server.epoch = 0
            outs = [None, None]

            def gather(i):
                outs[i] = pgs[i].all_gather_map(
                    f"p{i}".encode(), tag="fence-test",
                    allow_shrunk=True)

            ts = [threading.Thread(target=gather, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=20)
            assert outs[0] is not None and outs[1] is not None
            by_rank, epoch, dead = outs[1]
            assert dead == [2] and epoch >= 1
            assert sorted(by_rank) == [0, 1]
            assert pgs[1].epoch >= 1  # resynced by the rejection
        finally:
            for pg in pgs:
                pg.close()

    def test_restarted_rank_gets_fresh_identity(self, fast_backoff):
        """A restarted rank re-registers under a fresh incarnation (epoch
        bumps again); frames from its previous life are rejected typed
        instead of resurrecting with stale shuffle state."""
        coord, pgs = _make_group(2)
        reborn = None
        try:
            old = pgs[1]
            assert old.inc == 0
            _silently_kill(old)
            _wait_declared(pgs[0], 1)
            e_death = coord.epoch
            reborn = ProcessGroup(1, 2, ("127.0.0.1", coord.port),
                                  heartbeat_interval=0.1)
            assert reborn.inc == 1  # fresh identity
            assert coord.epoch > e_death  # rejoin bumped the epoch
            # the ZOMBIE's old-incarnation frame is rejected typed
            with pytest.raises(PeerLostError, match="stale incarnation"):
                old.barrier(tag="zombie-barrier")
            # the reborn rank participates normally
            outs = [None, None]

            def go(i, pg):
                outs[i] = pg.barrier(tag="rejoin-barrier",
                                     allow_shrunk=True)

            ts = [threading.Thread(target=go, args=(0, pgs[0])),
                  threading.Thread(target=go, args=(1, reborn))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=20)
            assert outs[0] is not None and outs[1] is not None
        finally:
            if reborn is not None:
                reborn.close()
            for pg in pgs:
                pg.close()

    def test_stale_epoch_fetch_rejected_by_peer_server(self, fast_backoff,
                                                       tmp_path):
        """Data-plane fencing: a fetch carrying an older epoch than the
        serving rank's membership view is rejected — a zombie cannot
        keep pulling shuffle state."""
        coord, pgs = _make_group(2)
        try:
            sh = DcnShuffle(pgs[0], 2, str(tmp_path / "r0"))
            sh.local.write_partition(0, pa.table({"x": [1, 2]}))
            sh.local.finish_writes()
            pgs[0]._server.epoch = 3  # rank 0 has seen epoch 3
            pgs[1].epoch = 1          # rank 1's view is stale
            with pytest.raises(PeerFailedError, match="stale epoch"):
                pgs[1].fetch(0, sh.id, 0)
            pgs[1].epoch = 3          # resynced: the fetch serves
            assert pgs[1].fetch(0, sh.id, 0)
            sh.local.close()
        finally:
            for pg in pgs:
                pg.close()


# ---------------------------------------------------------------------------
# Dead-peer fast-fail (satellite: no backoff budget burned on a corpse).
# ---------------------------------------------------------------------------

class TestDeadPeerFastFail:
    def test_types(self):
        assert issubclass(PeerLostError, PeerFailedError)
        assert issubclass(PeerLostError, PermanentFault)
        # ISSUE 10 retyping: coordinator loss is TRANSIENT whenever a
        # standby successor exists (the failover protocol heals it);
        # only the no-standby flavor stays permanent (and it keeps the
        # transient base so generic coordinator-loss handlers catch
        # both — the permanent classification wins in transient_retry)
        assert issubclass(CoordinatorLostError, TransientFault)
        assert not issubclass(CoordinatorLostError, PermanentFault)
        assert issubclass(CoordinatorUnrecoverableError,
                          CoordinatorLostError)
        assert issubclass(CoordinatorUnrecoverableError, PermanentFault)

    def test_permanent_fault_fast_fails_typed(self, fast_backoff):
        conf = TpuConf(FAST)
        calls = []

        def dead_fetch():
            calls.append(1)
            raise PeerLostError("rank 1 declared dead")

        with budget_scope(conf) as budget:
            start_budget = budget.remaining
            t0 = time.monotonic()
            with pytest.raises(QueryFaulted) as ei:
                transient_retry(conf, "shuffle.fragment", dead_fetch,
                                desc="rank-1 part-00000")
            elapsed = time.monotonic() - t0
        # ONE attempt, no backoff sleeps, budget untouched, typed +
        # resubmittable — the exact opposite of riding the retry curve
        assert len(calls) == 1
        assert ei.value.resubmittable is True
        assert budget.remaining == start_budget
        assert elapsed < 0.5
        assert "permanent at this placement" in str(ei.value)

    def test_transient_peer_error_still_retries(self, fast_backoff):
        conf = TpuConf(FAST)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise PeerFailedError("connection hiccup")
            return "ok"

        assert transient_retry(conf, "shuffle.fragment", flaky) == "ok"
        assert len(calls) == 2  # hiccups keep the backoff path

    def test_check_peers_raises_peer_lost(self, fast_backoff):
        coord, pgs = _make_group(2)
        try:
            _silently_kill(pgs[1])
            _wait_declared(pgs[0], 1)
            with pytest.raises(PeerLostError):
                pgs[0].check_peers()
            with pytest.raises(PeerLostError):
                pgs[0].fetch(1, "shuffle-1", 0)
        finally:
            for pg in pgs:
                pg.close()


# ---------------------------------------------------------------------------
# Coordinator loss: typed, prompt; PERMANENT only in the no-standby case.
# ---------------------------------------------------------------------------

class TestCoordinatorLost:
    def test_closed_coordinator_fails_requests_promptly(self,
                                                        fast_backoff):
        """World=1 is the no-standby case: coordinator loss stays a
        typed PermanentFault (CoordinatorUnrecoverableError) and is
        detected promptly — nowhere near waitTimeout."""
        coord, pgs = _make_group(1, wait_timeout=60.0)
        pg = pgs[0]
        try:
            coord.close()
            t0 = time.monotonic()
            with pytest.raises(CoordinatorUnrecoverableError):
                pg.barrier(tag="after-death")
            # typed and PROMPT: nowhere near the 60 s waitTimeout
            assert time.monotonic() - t0 < 5.0
            assert pg.coordinator_lost
            with pytest.raises(CoordinatorUnrecoverableError):
                pg.check_peers()
        finally:
            pg.close()

    def test_heartbeat_loop_flags_lost_coordinator(self, fast_backoff):
        coord, pgs = _make_group(1)
        pg = pgs[0]
        try:
            coord.close()
            deadline = time.monotonic() + 10
            while not pg.coordinator_lost and time.monotonic() < deadline:
                time.sleep(0.05)
            assert pg.coordinator_lost
        finally:
            pg.close()

    def test_standby_disabled_stays_permanent(self, fast_backoff):
        """The escape hatch: dcn.coordinator.standby=false restores the
        single-point-of-failure behavior even when survivors exist."""
        TpuConf.set_session("spark.rapids.tpu.dcn.coordinator.standby",
                            False)
        try:
            coord, pgs = _make_group(2, hb_timeout=0.6)
            try:
                coord.close()
                with pytest.raises(CoordinatorUnrecoverableError):
                    pgs[1].barrier(tag="no-standby")
                assert pgs[1].coordinator_lost
            finally:
                for pg in pgs:
                    pg.close()
        finally:
            TpuConf.unset_session(
                "spark.rapids.tpu.dcn.coordinator.standby")


# ---------------------------------------------------------------------------
# Coordinator failover: journal replay + successor takeover (the tier-1
# thread-rank simulation the acceptance criteria require on every run).
# ---------------------------------------------------------------------------

def _kill_coordinator_host(coord, pg, mode="freeze"):
    """Thread-rank analog of dcn.coordinator_kill: the hosting rank dies
    with its coordinator — silent (freeze: requests held forever, the
    worst case) or prompt (close: sockets fail fast)."""
    pg._closed = True
    pg._server.freeze()
    if mode == "freeze":
        coord.freeze()
    else:
        coord.close()


@pytest.fixture()
def failover_conf(fast_backoff):
    """Shrink the pg-side liveness horizon (heartbeat-reply recv
    timeout rides the conf) so frozen-coordinator detection is
    test-speed."""
    TpuConf.set_session("spark.rapids.tpu.dcn.heartbeatTimeout", 0.8)
    yield
    TpuConf.unset_session("spark.rapids.tpu.dcn.heartbeatTimeout")


class TestCoordinatorFailover:
    def test_journal_replay_and_successor_takeover(self, failover_conf):
        """World=3: a collective completes (journaled to the standby),
        the coordinator host dies SILENTLY, survivors fail over to the
        deterministic successor (rank 1 self-promotes from the
        journal), the in-flight collective completes over the alive
        membership, and the pre-death collective REPLAYS
        byte-identically from the restored journal."""
        coord, pgs = _make_group(3, hb_timeout=0.6)
        try:
            # one completed allgather before the death: its record must
            # survive into the successor via the journal stream
            outs = [None, None, None]

            def gather(i, tag):
                outs[i] = pgs[i].all_gather_map(
                    f"payload-{i}".encode(), tag=tag, allow_shrunk=True)

            ts = [threading.Thread(target=gather, args=(i, "pre-kill"))
                  for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert all(o is not None for o in outs)
            pre_epoch = coord.epoch
            # the journal reached the standby (write-ahead of replies)
            deadline = time.monotonic() + 10
            while pgs[1]._server.journal is None \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            journal = pgs[1]._server.journal
            assert journal is not None
            assert any(rec["tag"] == "pre-kill"
                       for rec in journal["completed"])

            s0 = QueryStats.get().snapshot()
            _kill_coordinator_host(coord, pgs[0], mode="freeze")

            # survivors run the next collective: their heartbeat threads
            # detect the frozen coordinator, rank 1 promotes, rank 2
            # re-dials it, and the collective completes over {1, 2}
            outs = [None, None, None]
            ts = [threading.Thread(target=gather, args=(i, "post-kill"))
                  for i in (1, 2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert outs[1] is not None and outs[2] is not None
            by_rank, epoch, dead = outs[1]
            assert sorted(by_rank) == [1, 2]
            assert 0 in dead
            assert epoch > pre_epoch  # epoch continuity across takeover
            assert outs[1] == outs[2]
            # both survivors performed (or joined) exactly one failover
            assert pgs[1].coord_rank == 1 and pgs[2].coord_rank == 1
            assert pgs[1].coordinator is not None  # promoted
            d = QueryStats.delta_since(s0)
            assert d["coordinator_failovers"] >= 2

            # journal REPLAY: rank 2 re-sends the pre-death tag (the
            # lost-reply shape) and gets the original bytes back
            msg, payload = pgs[2]._request(
                {"op": "allgather", "tag": "pre-kill"}, b"ignored")
            ranks = [int(r) for r in msg["ranks"]]
            parts = {}
            pos = 0
            for r, ln in zip(ranks, msg["lens"]):
                parts[r] = payload[pos:pos + ln]
                pos += ln
            assert parts == {0: b"payload-0", 1: b"payload-1",
                             2: b"payload-2"}
        finally:
            for pg in pgs:
                pg.close()

    def test_shuffle_survives_coordinator_host_death(self, failover_conf,
                                                     tmp_path):
        """World=2 mid-reduce coordinator-host death: the survivor
        self-promotes (it IS the standby), re-pulls the dead rank's
        fragments from durable map output, adopts its partitions, and
        accounts the failover — no row lost, no row doubled."""
        world, n_parts = 2, 4
        coord, pgs = _make_group(world, hb_timeout=0.6)
        shuffles = []
        try:
            shuffles = [DcnShuffle(pg, n_parts,
                                   str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            for rank, sh in enumerate(shuffles):
                for p in range(n_parts):
                    sh.write_partition(p, pa.table(
                        {"src": [rank] * 3, "part": [p] * 3,
                         "v": list(range(3))}))
            ts = [threading.Thread(target=sh.commit) for sh in shuffles]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert shuffles[0].committed == [0, 1]

            s0 = QueryStats.get().snapshot()
            _kill_coordinator_host(coord, pgs[0], mode="freeze")

            rows = []
            for p in shuffles[1].my_parts():
                rows.extend(shuffles[1].read_partition(p))
            adopted = shuffles[1].adopt_orphans()
            # committed=[0,1]: rank 0 owned the even partitions; its
            # death orphans them onto the sole survivor
            assert adopted == [0, 2]
            for p in adopted:
                rows.extend(shuffles[1].read_partition(p))
            got = pa.concat_tables(rows)
            assert got.num_rows == world * n_parts * 3
            by = sorted(zip(got.column("src").to_pylist(),
                            got.column("part").to_pylist()))
            assert by == sorted((r, p) for r in range(world)
                                for p in range(n_parts)
                                for _ in range(3))
            d = QueryStats.delta_since(s0)
            assert d["coordinator_failovers"] >= 1
            assert d["fragments_recomputed_remote"] >= 1
            assert d["partitions_reowned"] == len(adopted)
            assert pgs[1].coord_rank == 1
            shuffles[1].close()
            shuffles = []
        finally:
            for sh in shuffles:
                sh.local.close()
            for pg in pgs:
                pg.close()

    def test_coordinator_kill_injection_point(self, fast_backoff):
        """dcn.coordinator_kill (silent): the hosting rank's note_op
        kills coordinator + rank together — frozen, not closed — and
        the rank's own query unwinds typed."""
        INJECTOR.arm(schedule="dcn.coordinator_kill:1")
        coord, pgs = _make_group(1)
        try:
            with pytest.raises(PeerLostError, match="coordinator"):
                pgs[0].note_op()
            assert coord._frozen
            assert pgs[0]._server._frozen
        finally:
            INJECTOR.arm()
            for pg in pgs:
                pg.close()


# ---------------------------------------------------------------------------
# Cross-peer fragment recovery + orphan adoption (tier-1 single-process
# simulation of the killed-peer chaos run, over thread ranks).
# ---------------------------------------------------------------------------

class TestKilledPeerSimulation:
    def test_durable_repull_and_adoption(self, fast_backoff, tmp_path):
        world, n_parts = 2, 4
        coord, pgs = _make_group(world, hb_timeout=0.6)
        shuffles = []
        try:
            shuffles = [DcnShuffle(pg, n_parts, str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            for rank, sh in enumerate(shuffles):
                for p in range(n_parts):
                    sh.write_partition(p, pa.table(
                        {"src": [rank] * 3, "part": [p] * 3,
                         "v": list(range(3))}))
            ts = [threading.Thread(target=sh.commit) for sh in shuffles]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert shuffles[0].committed == [0, 1]
            assert sorted(shuffles[0].peer_dirs) == [0, 1]

            # rank 1 dies SILENTLY mid-shuffle (map output durable)
            _silently_kill(pgs[1])

            s0 = QueryStats.get().snapshot()
            rows = []
            # rank 0 reads its own partitions: rank 1's fragments come
            # back from the dead rank's DURABLE map output once the
            # fetch path gives up on the frozen server
            for p in shuffles[0].my_parts():
                for t_ in shuffles[0].read_partition(p):
                    rows.append(t_)
            # ... then adopts the dead rank's partitions
            adopted = shuffles[0].adopt_orphans()
            assert adopted == [p for p in range(n_parts) if p % 2 == 1]
            for p in adopted:
                for t_ in shuffles[0].read_partition(p):
                    rows.append(t_)
            got = pa.concat_tables(rows)
            # every row both ranks wrote is accounted for exactly once
            assert got.num_rows == world * n_parts * 3
            by = sorted(zip(got.column("src").to_pylist(),
                            got.column("part").to_pylist()))
            assert by == sorted((r, p) for r in range(world)
                                for p in range(n_parts)
                                for _ in range(3))
            d = QueryStats.delta_since(s0)
            assert d["fragments_recomputed_remote"] >= 1
            assert d["partitions_reowned"] == len(adopted)
            assert d["peers_lost"] == 1
            assert 1 in pgs[0].covered_dead
            shuffles[0].close()
            shuffles = []
        finally:
            for sh in shuffles:
                sh.local.close()
            for pg in pgs:
                pg.close()

    def test_precommit_death_fails_typed_resubmittable(self, fast_backoff,
                                                       tmp_path):
        """A rank dying BEFORE its map output commits loses its input
        contribution — commit fails typed + resubmittable, never
        silently wrong."""
        world = 2
        coord, pgs = _make_group(world, hb_timeout=0.5)
        try:
            shuffles = [DcnShuffle(pg, 2, str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            shuffles[0].write_partition(0, pa.table({"x": [1]}))
            _silently_kill(pgs[1])  # dies without committing
            _wait_declared(pgs[0], 1)
            with pytest.raises(PeerLostError, match="before committing"):
                shuffles[0].commit()
            # the typed failure rides the fast-fail protocol end to end
            with pytest.raises(QueryFaulted) as ei:
                transient_retry(TpuConf(FAST), "shuffle.fragment",
                                shuffles[0].commit)
            assert ei.value.resubmittable
            for sh in shuffles:
                sh.local.close()
        finally:
            for pg in pgs:
                pg.close()


# ---------------------------------------------------------------------------
# Scheduler resubmission: faulted -> resubmitted -> done lineage.
# ---------------------------------------------------------------------------

@pytest.fixture()
def resubmit_session(session):
    keys = [k for k in ALL_ENTRIES
            if k.startswith(("spark.rapids.tpu.faults.",
                             "spark.rapids.tpu.sql.trace."))]
    for k, v in FAST.items():
        session.conf.set(k, v)
    session.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    yield session
    for k in keys:
        session.conf.unset(k)
    INJECTOR.arm()


def _rows(sess, table):
    df = sess.create_dataframe(table)
    return sorted(df.group_by("k").agg(
        F.sum(F.col("v")).alias("s")).collect())


class TestSchedulerResubmission:
    def _flaky_query(self, sess, table, fail_times=1):
        state = {"calls": 0}

        def run():
            out = _rows(sess, table)  # a real traced attempt
            state["calls"] += 1
            if state["calls"] <= fail_times:
                # the shape a dead peer produces: a PermanentFault
                # surfaced through the fast-fail protocol
                transient_retry(None, "shuffle.fragment", lambda: (
                    _ for _ in ()).throw(
                        PeerLostError("rank 1 declared dead")))
            return out

        return run, state

    def test_faulted_resubmitted_done_lineage(self, resubmit_session):
        s = resubmit_session
        table = pa.table({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]})
        expect = _rows(s, table)
        run, state = self._flaky_query(s, table, fail_times=1)
        before = QueryStats.get().snapshot()
        sched = s.scheduler()
        base = sched.snapshot()["resubmitted"]
        handle = s.submit(run, label="killed-peer-query")
        assert handle.result(timeout=120) == expect
        # lineage: the faulted attempt was resubmitted, the retry ran to
        # done; the caller's one handle resolved with the final outcome
        assert handle.status == "done"
        assert handle.resubmits == 1
        assert state["calls"] == 2
        assert sched.snapshot()["resubmitted"] == base + 1
        assert sched.running() == 0
        # the faulted attempt's trace FINISHED with status 'resubmitted'
        # linked forward; the retry's trace links back
        attempts = handle.attempts
        assert len(attempts) == 1
        tr0 = attempts[0]["trace"]
        assert tr0 is not None and tr0.t_end is not None
        assert tr0.status == "resubmitted"
        assert tr0.attrs["resubmitted_to"] == "killed-peer-query~r1"
        tr1 = handle.trace()
        assert tr1 is not None
        assert tr1.attrs.get("resubmit_of") == "killed-peer-query"
        assert tr1.status == "ok"
        # stats reconciled: both attempts folded into the process
        # aggregate; the resubmission itself is counted
        d = QueryStats.delta_since(before)
        assert d["queries_resubmitted"] == 1
        get_catalog().assert_no_leaks()

    def test_resubmit_budget_exhausts_to_faulted(self, resubmit_session):
        s = resubmit_session
        table = pa.table({"k": [1], "v": [1.0]})
        run, state = self._flaky_query(s, table, fail_times=99)
        handle = s.submit(run, label="always-dead")
        with pytest.raises(QueryFaulted) as ei:
            handle.result(timeout=120)
        assert handle.status == "faulted"
        assert ei.value.resubmittable
        # default resubmit.max=1: one retry, then the typed failure
        assert handle.resubmits == 1
        assert state["calls"] == 2
        get_catalog().assert_no_leaks()

    def test_resubmit_disabled(self, resubmit_session):
        s = resubmit_session
        s.conf.set("spark.rapids.tpu.faults.resubmit.max", 0)
        table = pa.table({"k": [1], "v": [1.0]})
        run, state = self._flaky_query(s, table, fail_times=1)
        handle = s.submit(run, label="no-resubmit")
        with pytest.raises(QueryFaulted):
            handle.result(timeout=120)
        assert handle.status == "faulted"
        assert handle.resubmits == 0
        assert state["calls"] == 1

    def test_ordinary_faults_not_resubmitted(self, resubmit_session):
        """Transient exhaustion (NOT permanent-at-this-placement) keeps
        its faulted status — resubmission is reserved for failures a new
        placement can heal."""
        s = resubmit_session

        def run():
            transient_retry(TpuConf(FAST), "io.read", lambda: (
                _ for _ in ()).throw(OSError("EIO forever")))

        handle = s.submit(run, label="transient-exhaustion")
        with pytest.raises(QueryFaulted) as ei:
            handle.result(timeout=120)
        assert not ei.value.resubmittable
        assert handle.resubmits == 0
        assert handle.status == "faulted"


# ---------------------------------------------------------------------------
# Multi-process killed-peer chaos differential (the acceptance gate).
# ---------------------------------------------------------------------------

def _gen_shards(tmp_path, world, n=3000, seed=7):
    import numpy as np
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    tables = []
    for r in range(world):
        t = pa.table({
            "k": rng.integers(0, 37, n),
            "s": pa.array([["red", "green", "blue", None][i]
                           for i in rng.integers(0, 4, n)]),
            "v": rng.normal(size=n).round(3),
            "w": rng.normal(size=n).round(3),
        })
        pq.write_table(t, str(tmp_path / f"part-{r}.parquet"))
        tables.append(t)
    return pa.concat_tables(tables)


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_workers(tmp_path, world, query, kill_rank=-1, kill_mode="silent",
                   kill_after=1, kill_point="peer"):
    port = _free_port()
    out = str(tmp_path / "result")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = []
    for r in range(world):
        cmd = [sys.executable, os.path.join(REPO, "tests", "dcn_worker.py"),
               "--rank", str(r), "--world", str(world), "--port", str(port),
               "--data", str(tmp_path), "--out", out, "--query", query,
               "--hb-interval", "0.2", "--hb-timeout", "2.0",
               "--wait-timeout", "60"]
        if kill_rank >= 0:
            cmd += ["--kill-rank", str(kill_rank),
                    "--kill-after", str(kill_after),
                    "--kill-mode", kill_mode,
                    "--kill-point", kill_point]
        procs.append(subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT))
    return procs, out


@pytest.mark.slow
class TestKilledPeerChaosDifferential:
    @pytest.mark.parametrize("kill_mode", ["silent", "hard"])
    def test_killed_peer_mid_shuffle_differential(self, tmp_path, session,
                                                  kill_mode):
        """Kill rank 2 of 3 mid-shuffle: survivors complete with results
        IDENTICAL to the fault-free run, recovery accounting shows the
        remote re-pulls + re-owned partitions, and recovery time stays
        bounded by the liveness horizon, not the waitTimeout."""
        world, kill_rank = 3, 2
        whole = _gen_shards(tmp_path, world)

        # fault-free oracle #1: the single-process engine over all shards
        sess = srt.Session.get_or_create()
        df = sess.create_dataframe(whole)
        expect = (df.group_by("k", "s")
                  .agg(F.sum(F.col("v")).alias("sv"),
                       F.count_star().alias("c"),
                       F.avg(F.col("w")).alias("aw")).collect())

        # fault-free oracle #2: the SAME distributed engine with no kill
        # (the differential's exact baseline — float combine order
        # matches, so killed-run results must be IDENTICAL, unrounded)
        procs, out0 = _spawn_workers(tmp_path, world, "simple")
        for p in procs:
            log = p.communicate(timeout=300)[0].decode()
            assert p.returncode == 0, f"baseline worker:\n{log[-4000:]}"
        with open(f"{out0}.0") as f:
            baseline = json.load(f)
        for r in range(world):
            for suffix in ("", "stats."):
                try:
                    os.remove(f"{out0}.{suffix}{r}"
                              if suffix else f"{out0}.{r}")
                except OSError:
                    pass

        t0 = time.monotonic()
        procs, out = _spawn_workers(tmp_path, world, "simple",
                                    kill_rank=kill_rank,
                                    kill_mode=kill_mode)
        survivors = [p for r, p in enumerate(procs) if r != kill_rank]
        logs = {}
        for r, p in enumerate(procs):
            if r == kill_rank:
                continue
            logs[r] = p.communicate(timeout=300)[0].decode()
        elapsed = time.monotonic() - t0
        # the killed rank: hard mode exited already; silent mode lingers
        # as a zombie — reap it
        killed = procs[kill_rank]
        try:
            killed.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            killed.kill()
            killed.communicate(timeout=30)
        for r, p in enumerate(procs):
            if r != kill_rank:
                assert p.returncode == 0, \
                    f"survivor {r} failed:\n{logs[r][-4000:]}"

        results = {}
        stats = {}
        for r in range(world):
            if r == kill_rank:
                assert not os.path.exists(f"{out}.{r}")
                continue
            with open(f"{out}.{r}") as f:
                results[r] = json.load(f)
            with open(f"{out}.stats.{r}") as f:
                stats[r] = json.load(f)
        survivors_r = sorted(results)
        # every survivor returned the full, identical result
        assert results[survivors_r[0]] == results[survivors_r[1]]

        def key(r):
            return (r[0], r[1] is None, str(r[1]))

        def norm(rows, nd):
            return sorted(
                ((k, s, round(float(sv), nd), c, round(float(aw), nd))
                 for k, s, sv, c, aw in rows), key=key)
        # THE differential: killed peer -> answers IDENTICAL (exact, no
        # rounding) to the fault-free distributed run — the adopted
        # partitions' fragments combine in the same order the dead rank
        # would have combined them
        got = sorted(results[survivors_r[0]], key=key)
        assert got == sorted(baseline, key=key)
        # sanity vs the single-process oracle (float combine order
        # differs across engines -> coarse rounding)
        assert norm(results[survivors_r[0]], 4) == norm(expect, 4)
        # recovery is attributable: the dead rank's fragments were
        # re-pulled from durable map output and its partitions re-owned
        total = {k: sum(s[k] for s in stats.values())
                 for k in stats[survivors_r[0]]}
        assert total["peers_lost"] >= 1
        assert total["fragments_recomputed_remote"] >= 1
        assert total["partitions_reowned"] >= 1
        # bounded recovery: well under the 60 s waitTimeout path the old
        # code would have burned per collective
        assert elapsed < 240, f"recovery took {elapsed:.0f}s"


@pytest.mark.slow
class TestCoordinatorKillChaosDifferential:
    @pytest.mark.parametrize("kill_mode", ["silent", "hard"])
    def test_coordinator_killed_mid_query_differential(self, tmp_path,
                                                       session, kill_mode):
        """Kill the COORDINATOR HOST (rank 0 of 3) mid-query: survivors
        fail over to the standby (rank 1 promotes from the streamed
        journal), complete the in-flight collectives there, recover
        rank 0's committed map output durably, and return results
        byte-identical to the fault-free distributed run.  Failover is
        attributable: coordinator_failovers in the stats sidecars, and
        both survivors agree on a bumped epoch + the successor's rank.
        Silent mode freezes coordinator AND peer server (detection is
        purely liveness timeouts — the worst case); hard mode exits the
        hosting process."""
        world, kill_rank = 3, 0
        _gen_shards(tmp_path, world)

        # fault-free oracle: the SAME distributed engine with no kill
        procs, out0 = _spawn_workers(tmp_path, world, "simple")
        for p in procs:
            log = p.communicate(timeout=300)[0].decode()
            assert p.returncode == 0, f"baseline worker:\n{log[-4000:]}"
        with open(f"{out0}.0") as f:
            baseline = json.load(f)
        for r in range(world):
            for path in (f"{out0}.{r}", f"{out0}.stats.{r}"):
                try:
                    os.remove(path)
                except OSError:
                    pass

        t0 = time.monotonic()
        procs, out = _spawn_workers(tmp_path, world, "simple",
                                    kill_rank=kill_rank,
                                    kill_mode=kill_mode,
                                    kill_point="coordinator")
        logs = {}
        for r, p in enumerate(procs):
            if r == kill_rank:
                continue
            logs[r] = p.communicate(timeout=300)[0].decode()
        elapsed = time.monotonic() - t0
        killed = procs[kill_rank]
        try:
            killed.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            killed.kill()
            killed.communicate(timeout=30)
        for r, p in enumerate(procs):
            if r != kill_rank:
                assert p.returncode == 0, \
                    f"survivor {r} failed:\n{logs[r][-4000:]}"

        results, stats = {}, {}
        for r in range(world):
            if r == kill_rank:
                assert not os.path.exists(f"{out}.{r}")
                continue
            with open(f"{out}.{r}") as f:
                results[r] = json.load(f)
            with open(f"{out}.stats.{r}") as f:
                stats[r] = json.load(f)
        s1, s2 = sorted(results)
        assert results[s1] == results[s2]

        def key(row):
            return (row[0], row[1] is None, str(row[1]))
        # THE differential: coordinator loss mid-query -> answers
        # byte-identical (exact, no rounding) to the fault-free
        # distributed run
        assert sorted(results[s1], key=key) == sorted(baseline, key=key)
        # failover attributable: both survivors performed one, agree on
        # the successor, and share a bumped epoch (continuity)
        assert stats[s1]["coordinator_failovers"] >= 1
        assert stats[s2]["coordinator_failovers"] >= 1
        assert stats[s1]["coord_rank"] == stats[s2]["coord_rank"] == 1
        assert stats[s1]["final_epoch"] == stats[s2]["final_epoch"] >= 1
        # the dead host's committed map output was recovered durably
        total = {k: stats[s1][k] + stats[s2][k]
                 for k in ("peers_lost", "fragments_recomputed_remote",
                           "partitions_reowned")}
        assert total["peers_lost"] >= 1
        assert total["fragments_recomputed_remote"] >= 1
        assert total["partitions_reowned"] >= 1
        # bounded wall: liveness-horizon detection + takeover, nowhere
        # near the 60 s waitTimeout per collective
        assert elapsed < 240, f"failover recovery took {elapsed:.0f}s"

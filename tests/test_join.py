"""Joins: differential tests vs pandas for every join type × nulls ×
duplicates × key types.

Reference coverage model: JoinsSuite.scala + integration_tests join_test.py;
device algorithm is the sort-based union-gid join (plan/join_exec.py),
replacing the reference's cuDF gather-map hash joins
(GpuHashJoin.scala:104-383)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from .support import assert_rows_equal


def _rows(df):
    """pandas DataFrame -> list of tuples with None for NA."""
    out = []
    for t in df.itertuples(index=False):
        row = []
        for x in t:
            if x is None or (not isinstance(x, float) and pd.isna(x)):
                row.append(None)
            elif isinstance(x, float) and pd.isna(x):
                row.append(None)
            else:
                row.append(int(x) if isinstance(x, (np.integer,)) else x)
        out.append(tuple(row))
    return out


def _pandas_join(lpd, rpd, on, how):
    """SQL-semantics oracle: unlike SQL, pandas merge matches NA keys to
    each other, so null-key rows are stripped from the matching and
    reattached per outer-join semantics."""
    keys = [on] if isinstance(on, str) else list(on)
    lnull = lpd[keys].isna().any(axis=1)
    rnull = rpd[keys].isna().any(axis=1)
    lm, rm = lpd[~lnull], rpd[~rnull]
    if how == "inner":
        return lm.merge(rm, on=on, how="inner")
    if how == "left":
        return pd.concat([lm.merge(rm, on=on, how="left"),
                          lpd[lnull]], ignore_index=True)
    if how == "right":
        return pd.concat([lm.merge(rm, on=on, how="right"),
                          rpd[rnull]], ignore_index=True)
    if how == "full":
        return pd.concat([lm.merge(rm, on=on, how="outer"),
                          lpd[lnull], rpd[rnull]], ignore_index=True)
    raise ValueError(how)


LEFT = pd.DataFrame({
    "k": pd.array([1, 2, 2, 3, None, 5], dtype="Int64"),
    "lv": [10, 20, 21, 30, 40, 50],
})
RIGHT = pd.DataFrame({
    "k": pd.array([2, 2, 3, 4, None], dtype="Int64"),
    "rv": [200, 201, 300, 400, 500],
})


@pytest.fixture(scope="module")
def dfs(session):
    lt = pa.table({"k": pa.array(LEFT["k"], type=pa.int64()),
                   "lv": pa.array(LEFT["lv"], type=pa.int64())})
    rt = pa.table({"k": pa.array(RIGHT["k"], type=pa.int64()),
                   "rv": pa.array(RIGHT["rv"], type=pa.int64())})
    return (session.create_dataframe(lt), session.create_dataframe(rt))


class TestEquiJoins:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
    def test_vs_pandas(self, dfs, how):
        ldf, rdf = dfs
        got = ldf.join(rdf, on="k", how=how).collect()
        expect = _rows(_pandas_join(LEFT, RIGHT, "k", how))
        assert_rows_equal(got, expect)

    def test_semi(self, dfs):
        ldf, rdf = dfs
        got = ldf.join(rdf, on="k", how="semi").collect()
        keys = set(RIGHT["k"].dropna())
        expect = _rows(LEFT[LEFT["k"].isin(keys)])
        assert_rows_equal(got, expect)

    def test_anti(self, dfs):
        ldf, rdf = dfs
        got = ldf.join(rdf, on="k", how="anti").collect()
        keys = set(RIGHT["k"].dropna())
        mask = ~LEFT["k"].isin(keys) | LEFT["k"].isna()
        expect = _rows(LEFT[mask])
        assert_rows_equal(got, expect)

    def test_runs_on_tpu(self, fresh_session):
        fresh_session.conf.set(
            "spark.rapids.tpu.test.validateExecsOnTpu", True)
        ldf = fresh_session.create_dataframe({"k": [1, 2], "a": [1.0, 2.0]})
        rdf = fresh_session.create_dataframe({"k": [2, 3], "b": [5.0, 6.0]})
        got = ldf.join(rdf, on="k", how="inner").collect()
        assert got == [(2, 2.0, 5.0)]


class TestJoinEdgeCases:
    def test_empty_right(self, session):
        ldf = session.create_dataframe({"k": [1, 2], "a": [1.0, 2.0]})
        rdf = session.create_dataframe(
            pa.table({"k": pa.array([], type=pa.int64()),
                      "b": pa.array([], type=pa.float64())}))
        assert ldf.join(rdf, on="k", how="inner").collect() == []
        got = ldf.join(rdf, on="k", how="left").collect()
        assert_rows_equal(got, [(1, 1.0, None), (2, 2.0, None)])

    def test_empty_left(self, session):
        ldf = session.create_dataframe(
            pa.table({"k": pa.array([], type=pa.int64()),
                      "a": pa.array([], type=pa.float64())}))
        rdf = session.create_dataframe({"k": [1], "b": [9.0]})
        assert ldf.join(rdf, on="k", how="inner").collect() == []
        got = ldf.join(rdf, on="k", how="right").collect()
        assert_rows_equal(got, [(1, None, 9.0)])

    def test_duplicate_heavy(self, session):
        rng = np.random.default_rng(11)
        lpd = pd.DataFrame({"k": rng.integers(0, 20, 500),
                            "a": rng.integers(0, 1000, 500)})
        rpd = pd.DataFrame({"k": rng.integers(0, 20, 300),
                            "b": rng.integers(0, 1000, 300)})
        ldf = session.create_dataframe(lpd)
        rdf = session.create_dataframe(rpd)
        got = ldf.join(rdf, on="k", how="inner").collect()
        expect = _rows(lpd.merge(rpd, on="k", how="inner"))
        assert_rows_equal(got, expect)

    def test_multi_key(self, session):
        lpd = pd.DataFrame({"a": [1, 1, 2, 2], "b": [1, 2, 1, 2],
                            "lv": [1, 2, 3, 4]})
        rpd = pd.DataFrame({"a": [1, 2, 2], "b": [2, 1, 9],
                            "rv": [10, 20, 30]})
        got = session.create_dataframe(lpd).join(
            session.create_dataframe(rpd), on=["a", "b"],
            how="inner").collect()
        expect = _rows(lpd.merge(rpd, on=["a", "b"], how="inner"))
        assert_rows_equal(got, expect)

    def test_mixed_key_types(self, session):
        # int32 keys joined with int64 keys promote to int64
        lt = pa.table({"k": pa.array([1, 2, 3], type=pa.int32()),
                       "a": pa.array([1.0, 2.0, 3.0])})
        rt = pa.table({"k": pa.array([2, 3, 4], type=pa.int64()),
                       "b": pa.array([20.0, 30.0, 40.0])})
        got = session.create_dataframe(lt).join(
            session.create_dataframe(rt), on="k", how="inner").collect()
        assert_rows_equal(got, [(2, 2.0, 20.0), (3, 3.0, 30.0)])

    def test_float_keys_nan(self, session):
        # Spark joins treat NaN as equal to NaN
        lt = pa.table({"k": pa.array([1.0, float("nan"), 2.0]),
                       "a": pa.array([1, 2, 3], type=pa.int64())})
        rt = pa.table({"k": pa.array([float("nan"), 2.0]),
                       "b": pa.array([10, 20], type=pa.int64())})
        got = session.create_dataframe(lt).join(
            session.create_dataframe(rt), on="k", how="inner").collect()
        ks = sorted((3, 20) if (k == k) else (2, 10) for k, a, b in
                    [(r[0], r[1], r[2]) for r in got])
        assert len(got) == 2
        vals = sorted((r[1], r[2]) for r in got)
        assert vals == [(2, 10), (3, 20)]

    def test_cross_join(self, session):
        ldf = session.create_dataframe({"a": [1, 2]})
        rdf = session.create_dataframe({"b": [10, 20, 30]})
        got = ldf.cross_join(rdf).collect()
        assert len(got) == 6
        assert set(got) == {(a, b) for a in [1, 2] for b in [10, 20, 30]}

    def test_full_join_unmatched_both_sides(self, session):
        lpd = pd.DataFrame({"k": [1, 2], "a": [1.0, 2.0]})
        rpd = pd.DataFrame({"k": [2, 3], "b": [20.0, 30.0]})
        got = session.create_dataframe(lpd).join(
            session.create_dataframe(rpd), on="k", how="full").collect()
        expect = _rows(lpd.merge(rpd, on="k", how="outer"))
        assert_rows_equal(got, expect)

    def test_multi_batch_join(self, fresh_session):
        fresh_session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 100)
        rng = np.random.default_rng(3)
        lpd = pd.DataFrame({"k": rng.integers(0, 50, 1000),
                            "a": np.arange(1000)})
        rpd = pd.DataFrame({"k": rng.integers(0, 50, 400),
                            "b": np.arange(400)})
        got = fresh_session.create_dataframe(lpd).join(
            fresh_session.create_dataframe(rpd), on="k", how="left").collect()
        expect = _rows(lpd.merge(rpd, on="k", how="left"))
        assert_rows_equal(got, expect)

    def test_string_payload_carried(self, session):
        # string PAYLOAD columns ride through a device join host-side
        lt = pa.table({"k": pa.array([1, 2, 3], type=pa.int64()),
                       "name": pa.array(["a", "b", None])})
        rt = pa.table({"k": pa.array([2, 3], type=pa.int64()),
                       "tag": pa.array(["x", "y"])})
        got = session.create_dataframe(lt).join(
            session.create_dataframe(rt), on="k", how="left").collect()
        assert_rows_equal(got, [(1, "a", None), (2, "b", "x"),
                                (3, None, "y")])

    def test_inner_with_residual_condition(self, session):
        import spark_rapids_tpu.plan.logical as L
        from spark_rapids_tpu import exprs as E
        lpd = pd.DataFrame({"k": [1, 1, 2], "a": [5, 15, 25]})
        rpd = pd.DataFrame({"k": [1, 2], "lim": [10, 30]})
        ldf = session.create_dataframe(lpd)
        rdf = session.create_dataframe(rpd)
        node = L.Join(ldf._plan, rdf._plan,
                      [E.UnresolvedColumn("k")], [E.UnresolvedColumn("k")],
                      how="inner",
                      condition=(F.col("a") < F.col("lim")).expr)
        node.using = ["k"]
        from spark_rapids_tpu.sql.dataframe import DataFrame
        got = DataFrame(node, session).collect()
        assert_rows_equal(got, [(1, 5, 10), (2, 25, 30)])

    def test_cpu_left_join_with_residual_condition(self, session):
        # string keys force the CPU path; the residual must affect MATCHING
        # (unmatched rows null-padded), not post-filter the result
        import spark_rapids_tpu.plan.logical as L
        from spark_rapids_tpu import exprs as E
        from spark_rapids_tpu.sql.dataframe import DataFrame
        lt = pa.table({"k": pa.array(["a", "a", "b"]),
                       "v": pa.array([5, 15, 25], type=pa.int64())})
        rt = pa.table({"k": pa.array(["a", "b"]),
                       "lim": pa.array([10, 30], type=pa.int64())})
        ldf = session.create_dataframe(lt)
        rdf = session.create_dataframe(rt)
        node = L.Join(ldf._plan, rdf._plan,
                      [E.UnresolvedColumn("k")], [E.UnresolvedColumn("k")],
                      how="left",
                      condition=(F.col("v") < F.col("lim")).expr)
        node.using = ["k"]
        got = DataFrame(node, session).collect()
        # (a,15) matches key 'a' but fails v<lim -> null-padded, not dropped
        assert_rows_equal(got, [("a", 5, 10), ("a", 15, None),
                                ("b", 25, 30)])

    def test_cpu_semi_with_condition(self, session):
        import spark_rapids_tpu.plan.logical as L
        from spark_rapids_tpu import exprs as E
        from spark_rapids_tpu.sql.dataframe import DataFrame
        lt = pa.table({"k": pa.array(["a", "a", "b"]),
                       "v": pa.array([5, 15, 25], type=pa.int64())})
        rt = pa.table({"k": pa.array(["a", "b"]),
                       "lim": pa.array([10, 30], type=pa.int64())})
        node = L.Join(session.create_dataframe(lt)._plan,
                      session.create_dataframe(rt)._plan,
                      [E.UnresolvedColumn("k")], [E.UnresolvedColumn("k")],
                      how="semi",
                      condition=(F.col("v") < F.col("lim")).expr)
        node.using = ["k"]
        got = DataFrame(node, session).collect()
        assert_rows_equal(got, [("a", 5), ("b", 25)])

    def test_limit_above_scan_does_not_hang(self, session, tmp_path):
        # prefetch producer must shut down when the consumer abandons the
        # iterator (LIMIT breaks out early)
        import pyarrow.parquet as pq
        path = str(tmp_path / "t.parquet")
        pq.write_table(pa.table({"x": pa.array(range(100_000))}), path)
        df = session.read_parquet(path)
        for _ in range(30):  # would exhaust a leaked-thread queue quickly
            assert len(df.limit(5).collect()) == 5

    def test_string_join_key_on_device(self, session):
        """Bare string join keys run on device via dictionary codes
        (test_string_keys.py has the full matrix); computed string keys
        still fall back."""
        lt = pa.table({"k": pa.array(["a", "b"]),
                       "v": pa.array([1, 2], type=pa.int64())})
        rt = pa.table({"k": pa.array(["b", "c"]),
                       "w": pa.array([20, 30], type=pa.int64())})
        df = session.create_dataframe(lt).join(
            session.create_dataframe(rt), on="k", how="inner")
        s = df.explain_string()
        assert "join key" not in s  # no fallback reason reported
        got = df.collect()
        assert_rows_equal(got, [("b", 2, 20)])

"""ORC / JSON / CSV scan + write round trips with pushdown
(GpuOrcScan / GpuJsonScan / GpuCSVScan analogs)."""

import os

import pyarrow as pa
import pytest

from .support import assert_rows_equal


def F():
    from spark_rapids_tpu.sql import functions
    return functions


@pytest.fixture()
def t3():
    return pa.table({"a": pa.array([1, 2, 3, 4], type=pa.int64()),
                     "b": pa.array([1.5, None, -3.0, 0.25]),
                     "s": pa.array(["x", "y", None, "zz"])})


def test_orc_roundtrip(session, t3, tmp_path):
    out = str(tmp_path / "o")
    session.create_dataframe(t3).write.orc(out)
    back = session.read_orc(out)
    assert_rows_equal(back.collect(), [tuple(r) for r in zip(
        *[c.to_pylist() for c in t3.columns])])


def test_orc_column_pruning_plan(session, t3, tmp_path):
    f = F()
    out = str(tmp_path / "o")
    session.create_dataframe(t3).write.orc(out)
    df = session.read_orc(out).select("a").filter(f.col("a") > 2)
    plan = df.explain_string()
    assert "cols=['a']" in plan  # projection reached the source
    assert sorted(r[0] for r in df.collect()) == [3, 4]


def test_json_roundtrip(session, t3, tmp_path):
    out = str(tmp_path / "j")
    session.create_dataframe(t3).write.json(out)
    back = session.read_json(out)
    got = back.collect()
    # JSON writer omits null fields; reader re-infers them as null
    assert_rows_equal(got, [tuple(r) for r in zip(
        *[c.to_pylist() for c in t3.columns])])


def test_json_explicit_schema(session, tmp_path):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import Field, Schema
    p = str(tmp_path / "d")
    os.makedirs(p)
    with open(os.path.join(p, "a.json"), "w") as fh:
        fh.write('{"a": 1, "b": "x"}\n{"a": 2}\n')
    sch = Schema([Field("a", T.FLOAT64, True), Field("b", T.STRING, True)])
    back = session.read_json(p, schema=sch)
    assert_rows_equal(back.collect(), [(1.0, "x"), (2.0, None)])


def test_csv_pushdown(session, t3, tmp_path):
    f = F()
    out = str(tmp_path / "c")
    session.create_dataframe(t3.select(["a", "b"])).write.csv(out)
    df = session.read_csv(out).filter(f.col("a") >= 3).select("b")
    plan = df.explain_string()
    assert "pushdown" in plan
    assert sorted(r[0] for r in df.collect()) == [-3.0, 0.25]


def test_multi_file_csv(session, tmp_path):
    p = str(tmp_path / "m")
    os.makedirs(p)
    for i in range(3):
        with open(os.path.join(p, f"f{i}.csv"), "w") as fh:
            fh.write("a,b\n")
            fh.write(f"{i},{i * 1.5}\n")
    got = sorted(session.read_csv(p).collect())
    assert got == [(0, 0.0), (1, 1.5), (2, 3.0)]


class TestPathReplacement:
    """Remote-storage redirection (AlluxioUtils.scala analog): reader
    paths matching a configured prefix rewrite to the replacement
    mount before any filesystem access."""

    def test_prefix_rewrites_to_local_mount(self, fresh_session,
                                            tmp_path, rng):
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq
        sess = fresh_session
        mount = tmp_path / "mount" / "bucket"
        mount.mkdir(parents=True)
        t = pa.table({"a": np.arange(10, dtype=np.int64)})
        pq.write_table(t, str(mount / "f.parquet"))
        sess.conf.set(
            "spark.rapids.tpu.io.pathReplacementRules",
            f"s3://bucket=>{tmp_path}/mount/bucket,"
            f"gs://other=>/nonexistent")
        try:
            got = sess.read_parquet("s3://bucket/f.parquet").collect()
        finally:
            sess.conf.set(
                "spark.rapids.tpu.io.pathReplacementRules", "")
        assert [r[0] for r in got] == list(range(10))

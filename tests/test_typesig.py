"""TypeSig-driven tagging: declared expression signatures are enforced by
the planner, not just documented.

Reference: TypeChecks.scala:171 (TypeSig algebra), ExprChecks
(TypeChecks.scala:1125) — the same signature objects drive tagging AND
docs/supported_ops.md generation.
"""

import datetime

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.types import TypeSig


class TestSigAlgebra:
    def test_check_and_describe(self):
        sig = TypeSig.numeric + TypeSig.null
        assert sig.check(T.INT64) is None
        assert sig.check(T.FLOAT64) is None
        assert "not supported" in sig.check(T.TIMESTAMP)
        assert "not supported" in sig.check(T.STRING)
        assert "decimal" in TypeSig.device_compute.check(T.decimal(38, 2))
        assert "int" in sig.describe() and "double" in sig.describe()

    def test_add_subtract(self):
        s = TypeSig.common - TypeSig.string
        assert s.check(T.STRING) is not None
        assert s.check(T.INT32) is None


class TestSigDrivenTagging:
    def test_math_on_timestamp_falls_back_with_sig_reason(self, session):
        df = session.create_dataframe(
            {"ts": [datetime.datetime(2024, 1, 1)], "x": [4.0]})
        plan = df.select(F.sqrt(F.col("ts")).alias("s")).explain_string()
        assert "type timestamp is not supported" in plan
        assert "Sqrt input ts" in plan

    def test_math_on_double_stays_on_device(self, session):
        df = session.create_dataframe({"x": [4.0, 9.0]})
        q = df.select(F.sqrt(F.col("x")).alias("s"))
        plan = q.explain_string()
        assert "not supported" not in plan
        assert [r[0] for r in q.collect()] == [2.0, 3.0]

    def test_fallback_still_computes(self, session):
        """A sig rejection must fall back, not fail (RapidsMeta contract:
        tagged-no nodes run on CPU with reasons)."""
        df = session.create_dataframe(
            {"ts": [datetime.datetime(1970, 1, 1, 0, 0, 4)]})
        rows = df.select(F.sqrt(F.col("ts")).alias("s")).collect()
        assert len(rows) == 1  # value is CPU-path defined; shape matters


class TestDecimal128Tier:
    """decimal(18 < p <= 38) rides as DEVICE two-limb int64 columns
    (r5, ops/wide_decimal.py): projection/sort/add/compare/sum stay on
    device; only >38 or unsupported wide ops fall back."""

    def _df(self, session):
        import decimal

        import pyarrow as pa
        D = decimal.Decimal
        t = pa.table({
            "x": pa.array([D("99999999999999999999.50"), D("1.25"), None],
                          type=pa.decimal128(38, 2)),
            "y": [2.0, 3.0, 4.0]})
        return session.create_dataframe(t)

    def test_passthrough_projection_stays_on_device_plan(self, session):
        df = self._df(session)
        q = df.select("x", "y")
        assert "!" not in q.explain_string().splitlines()[2]
        rows = q.collect()
        assert [str(r[0]) for r in rows[:2]] == \
            ["99999999999999999999.50", "1.25"]

    def test_sort_key_on_device(self, session):
        # r5: decimal(38) rides as two int64 limbs — the sort contributes
        # (hi, lo-unsigned) operands and stays ON DEVICE
        import decimal
        df = self._df(session)
        q = df.sort("x")
        plan = q.explain_string()
        assert "host-carried column x" not in plan
        rows = q.collect()
        assert rows[0][0] is None  # nulls first (asc default)
        assert rows[1][0] == decimal.Decimal("1.25")
        assert rows[2][0] == decimal.Decimal("99999999999999999999.50")

    def test_wide_plus_float_on_device(self, session):
        # r5: decimal(38) + float promotes to float64 on device (lossy
        # like Spark's Decimal.toDouble) instead of CPU-falling-back
        from spark_rapids_tpu.sql import functions as F
        df = self._df(session)
        q = df.select((F.col("x") + F.col("y")).alias("z"))
        plan = q.explain_string()
        assert "!" not in plan.splitlines()[2], plan
        rows = q.collect()
        assert abs(rows[1][0] - 4.25) < 1e-9
        assert rows[2][0] is None


class TestSigsGenerateDocs:
    def test_supported_ops_include_sig_columns(self):
        from spark_rapids_tpu.docs import supported_ops_md
        md = supported_ops_md()
        assert "| Input types | Output types |" in md
        # Sqrt row shows its restricted numeric input / fp output sig
        row = next(ln for ln in md.splitlines() if ln.startswith("| Sqrt "))
        assert "decimal" in row and "float" in row
        assert "timestamp" not in row

"""tools/trace_report.py + tools/bench_compare.py + span-timing lint."""

import json

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F

from tools import bench_compare, trace_report
from tools.srtlint.engine import run as srtlint_run


@pytest.fixture()
def sess():
    s = srt.Session.get_or_create()
    yield s
    s.conf.unset("spark.rapids.tpu.sql.trace.enabled")


def _trace_file(sess, tmp_path):
    rng = np.random.default_rng(3)
    df = sess.create_dataframe({"k": rng.integers(0, 50, 30000),
                                "v": rng.random(30000)})
    q = (df.where(F.col("v") > 0.2)
         .group_by((F.col("k") % 7).cast("int").alias("g"))
         .agg(F.sum(F.col("v")).alias("s")))
    sess.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    try:
        q.collect()
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.trace.enabled")
    path = str(tmp_path / "q.trace.json")
    sess.last_trace().write(path)
    return path


# ---------------------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------------------

def test_trace_report_hot_operators_and_overlap(sess, tmp_path):
    path = _trace_file(sess, tmp_path)
    a = trace_report.analyze(trace_report.load(path))
    assert a["wall_s"] > 0
    assert a["operators"], "no per-operator rows"
    # per-operator self time is positive and sums to <= ~wall (nesting
    # subtracts children; on the serial CPU path nothing double-counts)
    assert a["self_total_s"] > 0
    assert a["self_total_s"] <= a["wall_s"] * 1.1
    # self-time accounts for the bulk of the query wall time
    assert a["self_coverage"] > 0.5
    assert a["blocking_fetches"] >= 1
    assert 0 < a["overlap_ratio"] <= 4.0
    out = trace_report.format_report(a)
    assert "hot operators" in out
    assert "blocking fetches:" in out
    assert "overlap:" in out
    assert "TpuScan" in out or "ScanExec" in out


def test_trace_report_main(sess, tmp_path, capsys):
    path = _trace_file(sess, tmp_path)
    assert trace_report.main([path]) == 0
    assert "hot operators" in capsys.readouterr().out
    assert trace_report.main([]) == 2


def test_trace_report_peer_fault_summary(sess, tmp_path):
    """A query that survived distributed failures gets a peers: line
    (QueryStats snapshot on the root event is authoritative); clean
    queries don't."""
    path = _trace_file(sess, tmp_path)
    data = trace_report.load(path)
    assert "peers:" not in trace_report.format_report(
        trace_report.analyze(data))
    for e in data["traceEvents"]:
        if e.get("cat") == "query":
            e.setdefault("args", {}).update({
                "peers_lost": 1, "fragments_recomputed_remote": 8,
                "partitions_reowned": 4, "queries_resubmitted": 1})
    a = trace_report.analyze(data)
    assert a["peers_lost"] == 1
    assert a["fragments_recomputed_remote"] == 8
    out = trace_report.format_report(a)
    assert ("peers: lost=1 remote_recomputed=8 reowned=4 "
            "resubmissions=1") in out


def test_trace_report_merged_concurrent(sess, tmp_path, capsys):
    """A merged multi-query trace renders per-query sections plus a
    contention summary instead of assuming one serial query."""
    from spark_rapids_tpu.utils import tracing
    sess.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    try:
        rng = np.random.default_rng(7)
        df = sess.create_dataframe({"k": rng.integers(0, 20, 10000),
                                    "v": rng.random(10000)})
        q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
        handles = [sess.submit(q, label=f"conc-{i}") for i in range(3)]
        for h in handles:
            h.result(timeout=60)
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.trace.enabled")
    traces = [h.trace() for h in handles]
    assert all(t is not None for t in traces)
    path = str(tmp_path / "merged.trace.json")
    tracing.write_merged(traces, path)
    data = trace_report.load(path)
    # one pid + spanTrees entry per query
    assert len(data["spanTrees"]) == 3
    assert {st["pid"] for st in data["spanTrees"]} == {1, 2, 3}
    subs, span_trees = trace_report.split_queries(data)
    assert len(subs) == 3 and span_trees is not None
    for sub in subs:
        a = trace_report.analyze(sub)
        assert a["wall_s"] > 0
        assert a["operators"], "per-query section lost its operators"
    c = trace_report.contention(span_trees)
    assert c["queries"] == 3
    assert c["span_s"] > 0
    assert c["sum_walls_s"] >= c["span_s"] * 0.99
    assert 1 <= c["peak_concurrency"] <= 3
    assert c["statuses"] == {"ok": 3}
    assert trace_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "contention summary (3 concurrent queries)" in out
    assert "aggregate throughput" in out
    # a single-query trace still renders the old way
    single = _trace_file(sess, tmp_path)
    subs1, st1 = trace_report.split_queries(trace_report.load(single))
    assert len(subs1) == 1 and st1 is None


# ---------------------------------------------------------------------------------
# bench_compare
# ---------------------------------------------------------------------------------

def _bench(value, **queries):
    agg = {"metric": "tpch22_tpcds22_geomean_speedup_vs_cpu",
           "value": value, "unit": "x"}
    agg.update(queries)
    return agg


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_bench_compare_ok(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _bench(
        4.0, q1={"engine_s": 1.0}, q6={"engine_s": 0.5}))
    new = _write(tmp_path, "new.json", _bench(
        4.1, q1={"engine_s": 1.05}, q6={"engine_s": 0.45}))
    assert bench_compare.main([old, new]) == 0
    assert "OK" in capsys.readouterr().out


def test_bench_compare_query_regression(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new = _write(tmp_path, "new.json", _bench(4.0, q1={"engine_s": 1.5}))
    assert bench_compare.main([old, new]) == 1
    assert "REGRESSION" in capsys.readouterr().err


def test_bench_compare_aggregate_regression(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new = _write(tmp_path, "new.json", _bench(3.0, q1={"engine_s": 1.0}))
    assert bench_compare.main([old, new]) == 1
    err = capsys.readouterr().err
    assert "aggregate geomean" in err


def test_bench_compare_errored_query_is_regression(tmp_path):
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new = _write(tmp_path, "new.json", _bench(
        4.0, q1={"error": "timeout after 300s"}))
    assert bench_compare.main([old, new]) == 1


def test_bench_compare_thresholds_and_driver_wrapper(tmp_path):
    # 30% slower passes with a 50% threshold
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new_obj = _bench(4.0, q1={"engine_s": 1.3})
    new = _write(tmp_path, "new.json", new_obj)
    assert bench_compare.main(
        [old, new, "--max-query-regress-pct", "50"]) == 0
    # the BENCH_r0N driver capture shape: {"parsed": {...}} and
    # {"tail": "...\n<json line>"}
    wrapped = _write(tmp_path, "wrapped.json",
                     {"rc": 0, "parsed": new_obj})
    tail = _write(tmp_path, "tail.json",
                  {"rc": 124, "parsed": None,
                   "tail": "noise\n" + json.dumps(new_obj)})
    assert bench_compare.main(
        [old, wrapped, "--max-query-regress-pct", "50"]) == 0
    assert bench_compare.main(
        [old, tail, "--max-query-regress-pct", "50"]) == 0


def test_bench_compare_bad_file(tmp_path):
    bad = _write(tmp_path, "bad.json", {"nothing": True})
    ok = _write(tmp_path, "ok.json", _bench(4.0))
    assert bench_compare.main([bad, ok]) == 2


# ---------------------------------------------------------------------------------
# span-timing lint
# ---------------------------------------------------------------------------------

def test_span_timing_lint_clean_and_detects(tmp_path):
    from tools.srtlint import run_for_pytest
    assert [f for f in run_for_pytest().failing
            if f.rule == "span-timing"] == []
    # a synthetic violation is caught; a REASONED marker suppresses,
    # a bare marker does not (every suppression must say why)
    pkg = tmp_path / "spark_rapids_tpu"
    (pkg / "plan").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "plan" / "bad.py").write_text(
        "import time\n"
        "t0 = time.perf_counter()\n"
        "ok = time.monotonic()  # span-api-ok (a seed, not timing)\n"
        "t1 = time.time()  # span-api-ok\n")
    report = srtlint_run(str(tmp_path), roots=("spark_rapids_tpu",),
                         rules=["span-timing"])
    assert sorted(f.line for f in report.failing) == [2, 4]
    assert "no reason" in [f for f in report.failing
                           if f.line == 4][0].message
    assert [f.line for f in report.suppressed] == [3]

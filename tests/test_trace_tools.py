"""tools/trace_report.py + tools/bench_compare.py + span-timing lint."""

import json

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F

from tools import bench_compare, trace_report
from tools.srtlint.engine import run as srtlint_run


@pytest.fixture()
def sess():
    s = srt.Session.get_or_create()
    yield s
    s.conf.unset("spark.rapids.tpu.sql.trace.enabled")


def _trace_file(sess, tmp_path):
    rng = np.random.default_rng(3)
    df = sess.create_dataframe({"k": rng.integers(0, 50, 30000),
                                "v": rng.random(30000)})
    q = (df.where(F.col("v") > 0.2)
         .group_by((F.col("k") % 7).cast("int").alias("g"))
         .agg(F.sum(F.col("v")).alias("s")))
    sess.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    try:
        q.collect()
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.trace.enabled")
    path = str(tmp_path / "q.trace.json")
    sess.last_trace().write(path)
    return path


# ---------------------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------------------

def test_trace_report_hot_operators_and_overlap(sess, tmp_path):
    path = _trace_file(sess, tmp_path)
    a = trace_report.analyze(trace_report.load(path))
    assert a["wall_s"] > 0
    assert a["operators"], "no per-operator rows"
    # per-operator self time is positive and sums to <= ~wall (nesting
    # subtracts children; on the serial CPU path nothing double-counts)
    assert a["self_total_s"] > 0
    assert a["self_total_s"] <= a["wall_s"] * 1.1
    # self-time accounts for the bulk of the query wall time
    assert a["self_coverage"] > 0.5
    assert a["blocking_fetches"] >= 1
    assert 0 < a["overlap_ratio"] <= 4.0
    out = trace_report.format_report(a)
    assert "hot operators" in out
    assert "blocking fetches:" in out
    assert "overlap:" in out
    assert "TpuScan" in out or "ScanExec" in out


def test_trace_report_main(sess, tmp_path, capsys):
    path = _trace_file(sess, tmp_path)
    assert trace_report.main([path]) == 0
    assert "hot operators" in capsys.readouterr().out
    assert trace_report.main([]) == 2


def test_trace_report_peer_fault_summary(sess, tmp_path):
    """A query that survived distributed failures gets a peers: line
    (QueryStats snapshot on the root event is authoritative); clean
    queries don't."""
    path = _trace_file(sess, tmp_path)
    data = trace_report.load(path)
    assert "peers:" not in trace_report.format_report(
        trace_report.analyze(data))
    for e in data["traceEvents"]:
        if e.get("cat") == "query":
            e.setdefault("args", {}).update({
                "peers_lost": 1, "fragments_recomputed_remote": 8,
                "partitions_reowned": 4, "queries_resubmitted": 1})
    a = trace_report.analyze(data)
    assert a["peers_lost"] == 1
    assert a["fragments_recomputed_remote"] == 8
    out = trace_report.format_report(a)
    assert ("peers: lost=1 remote_recomputed=8 reowned=4 "
            "resubmissions=1") in out


def test_trace_report_merged_concurrent(sess, tmp_path, capsys):
    """A merged multi-query trace renders per-query sections plus a
    contention summary instead of assuming one serial query."""
    from spark_rapids_tpu.utils import tracing
    sess.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    try:
        rng = np.random.default_rng(7)
        df = sess.create_dataframe({"k": rng.integers(0, 20, 10000),
                                    "v": rng.random(10000)})
        q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
        handles = [sess.submit(q, label=f"conc-{i}") for i in range(3)]
        for h in handles:
            h.result(timeout=60)
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.trace.enabled")
    traces = [h.trace() for h in handles]
    assert all(t is not None for t in traces)
    path = str(tmp_path / "merged.trace.json")
    tracing.write_merged(traces, path)
    data = trace_report.load(path)
    # one pid + spanTrees entry per query
    assert len(data["spanTrees"]) == 3
    assert {st["pid"] for st in data["spanTrees"]} == {1, 2, 3}
    subs, span_trees = trace_report.split_queries(data)
    assert len(subs) == 3 and span_trees is not None
    for sub in subs:
        a = trace_report.analyze(sub)
        assert a["wall_s"] > 0
        assert a["operators"], "per-query section lost its operators"
    c = trace_report.contention(span_trees)
    assert c["queries"] == 3
    assert c["span_s"] > 0
    assert c["sum_walls_s"] >= c["span_s"] * 0.99
    assert 1 <= c["peak_concurrency"] <= 3
    assert c["statuses"] == {"ok": 3}
    assert trace_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "contention summary (3 concurrent queries)" in out
    assert "aggregate throughput" in out
    # a single-query trace still renders the old way
    single = _trace_file(sess, tmp_path)
    subs1, st1 = trace_report.split_queries(trace_report.load(single))
    assert len(subs1) == 1 and st1 is None


# ---------------------------------------------------------------------------------
# bench_compare
# ---------------------------------------------------------------------------------

def _bench(value, **queries):
    agg = {"metric": "tpch22_tpcds22_geomean_speedup_vs_cpu",
           "value": value, "unit": "x"}
    agg.update(queries)
    return agg


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_bench_compare_ok(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _bench(
        4.0, q1={"engine_s": 1.0}, q6={"engine_s": 0.5}))
    new = _write(tmp_path, "new.json", _bench(
        4.1, q1={"engine_s": 1.05}, q6={"engine_s": 0.45}))
    assert bench_compare.main([old, new]) == 0
    assert "OK" in capsys.readouterr().out


def test_bench_compare_query_regression(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new = _write(tmp_path, "new.json", _bench(4.0, q1={"engine_s": 1.5}))
    assert bench_compare.main([old, new]) == 1
    assert "REGRESSION" in capsys.readouterr().err


def test_bench_compare_aggregate_regression(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new = _write(tmp_path, "new.json", _bench(3.0, q1={"engine_s": 1.0}))
    assert bench_compare.main([old, new]) == 1
    err = capsys.readouterr().err
    assert "aggregate geomean" in err


def test_bench_compare_errored_query_is_regression(tmp_path):
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new = _write(tmp_path, "new.json", _bench(
        4.0, q1={"error": "timeout after 300s"}))
    assert bench_compare.main([old, new]) == 1


def test_bench_compare_thresholds_and_driver_wrapper(tmp_path):
    # 30% slower passes with a 50% threshold
    old = _write(tmp_path, "old.json", _bench(4.0, q1={"engine_s": 1.0}))
    new_obj = _bench(4.0, q1={"engine_s": 1.3})
    new = _write(tmp_path, "new.json", new_obj)
    assert bench_compare.main(
        [old, new, "--max-query-regress-pct", "50"]) == 0
    # the BENCH_r0N driver capture shape: {"parsed": {...}} and
    # {"tail": "...\n<json line>"}
    wrapped = _write(tmp_path, "wrapped.json",
                     {"rc": 0, "parsed": new_obj})
    tail = _write(tmp_path, "tail.json",
                  {"rc": 124, "parsed": None,
                   "tail": "noise\n" + json.dumps(new_obj)})
    assert bench_compare.main(
        [old, wrapped, "--max-query-regress-pct", "50"]) == 0
    assert bench_compare.main(
        [old, tail, "--max-query-regress-pct", "50"]) == 0


def test_bench_compare_bad_file(tmp_path):
    bad = _write(tmp_path, "bad.json", {"nothing": True})
    ok = _write(tmp_path, "ok.json", _bench(4.0))
    assert bench_compare.main([bad, ok]) == 2


# ---------------------------------------------------------------------------------
# span-timing lint
# ---------------------------------------------------------------------------------

def test_span_timing_lint_clean_and_detects(tmp_path):
    from tools.srtlint import run_for_pytest
    assert [f for f in run_for_pytest().failing
            if f.rule == "span-timing"] == []
    # a synthetic violation is caught; a REASONED marker suppresses,
    # a bare marker does not (every suppression must say why)
    pkg = tmp_path / "spark_rapids_tpu"
    (pkg / "plan").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "plan" / "bad.py").write_text(
        "import time\n"
        "t0 = time.perf_counter()\n"
        "ok = time.monotonic()  # span-api-ok (a seed, not timing)\n"
        "t1 = time.time()  # span-api-ok\n")
    report = srtlint_run(str(tmp_path), roots=("spark_rapids_tpu",),
                         rules=["span-timing"])
    assert sorted(f.line for f in report.failing) == [2, 4]
    assert "no reason" in [f for f in report.failing
                           if f.line == 4][0].message
    assert [f.line for f in report.suppressed] == [3]


# ---------------------------------------------------------------------------------
# explain_slow + trace_report --why
# ---------------------------------------------------------------------------------

from spark_rapids_tpu.utils import recorder, telemetry  # noqa: E402
from tools import explain_slow, perfwatch  # noqa: E402


@pytest.fixture()
def fresh_recorder():
    recorder.reset_for_tests()
    telemetry.reset_for_tests()
    yield recorder.recorder()
    recorder.reset_for_tests()
    telemetry.reset_for_tests()


def _sealed_capture(rec, tmp_path, term="compile", excess=1.5):
    """A recorder-retained capture whose verdict names ``term``."""
    from spark_rapids_tpu.utils.tracing import QueryTrace
    rec.configure({
        "spark.rapids.tpu.recorder.enabled": True,
        "spark.rapids.tpu.recorder.maxQueries": 48,
        "spark.rapids.tpu.recorder.maxBytes": 32 << 20,
        "spark.rapids.tpu.sql.trace.dir": str(tmp_path),
    })

    def seal(wall, attrs):
        tr = QueryTrace(f"q[{term}]")
        tr.attrs.update(attrs)
        tr.t_end = tr.t0 + wall
        tr.status = "ok"
        rec.seal(tr, None, 0.01, True, False)

    for _ in range(3):
        seal(0.05, {f"{term}_s" if term != "h2d"
                    else "h2d_wait_s": 0.005})
    seal(2.0, {f"{term}_s" if term != "h2d"
               else "h2d_wait_s": excess})
    cap = rec.captures()[-1]
    assert cap.verdict == term
    return cap


class TestExplainSlow:
    def test_sealed_capture_is_authoritative(self, fresh_recorder,
                                             tmp_path):
        cap = _sealed_capture(fresh_recorder, tmp_path)
        res = explain_slow.analyze_path(cap.path)
        assert res["sealed"] is True
        assert res["verdict"] == "compile"
        assert res["capture_reason"] == "top_k"
        assert res["excess_s"] == pytest.approx(1.5, abs=0.1)
        out = explain_slow.format_why(res)
        assert "<-- dominant" in out
        assert "verdict: compile" in out
        assert "EWMA baseline" in out

    def test_unsealed_trace_recomputes_without_verdict(self, sess,
                                                       tmp_path):
        # a trace dumped with the recorder off predates the seal:
        # terms are recomputed offline, no baseline verdict is invented
        sess.conf.set("spark.rapids.tpu.recorder.enabled", False)
        try:
            path = _trace_file(sess, tmp_path)
        finally:
            sess.conf.unset("spark.rapids.tpu.recorder.enabled")
        res = explain_slow.analyze_path(path)
        assert res["sealed"] is False
        assert res["verdict"] is None
        assert res["terms"]["dispatch"] > 0
        out = explain_slow.format_why(res)
        assert "n/a" in out and "recomputed" in out

    def test_main_json_and_exit_codes(self, fresh_recorder, tmp_path,
                                      capsys):
        cap = _sealed_capture(fresh_recorder, tmp_path,
                              term="fetch_wait")
        assert explain_slow.main([cap.path, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out.strip())
        assert doc["verdict"] == "fetch_wait"
        bad = tmp_path / "nope.json"
        bad.write_text("{")
        assert explain_slow.main([str(bad)]) == 2

    def test_trace_report_why_section(self, fresh_recorder, tmp_path,
                                      capsys):
        cap = _sealed_capture(fresh_recorder, tmp_path,
                              term="queue_wait")
        assert trace_report.main([cap.path, "--why"]) == 0
        out = capsys.readouterr().out
        assert "why (root-cause attribution):" in out
        assert "verdict: queue_wait" in out

    def test_trace_report_why_on_plain_trace(self, sess, tmp_path,
                                             capsys):
        path = _trace_file(sess, tmp_path)
        assert trace_report.main([path, "--why"]) == 0
        out = capsys.readouterr().out
        assert "hot operators" in out  # the timing report still leads
        assert "why (root-cause attribution):" in out


# ---------------------------------------------------------------------------------
# bench_compare compile gate
# ---------------------------------------------------------------------------------

class TestCompileGate:
    def test_warm_recompile_is_a_regression(self, tmp_path, capsys):
        old = _write(tmp_path, "old.json", _bench(
            4.0, q1={"engine_s": 1.0, "compiles_warm": 0}))
        new = _write(tmp_path, "new.json", _bench(
            4.0, q1={"engine_s": 1.0, "compiles_warm": 2}))
        assert bench_compare.main([old, new]) == 1
        err = capsys.readouterr().err
        assert "compiles_warm 0 -> 2" in err
        # an explicit allowance admits it
        assert bench_compare.main(
            [old, new, "--max-compile-increase", "2"]) == 0

    def test_compile_improvement_is_a_note(self, tmp_path, capsys):
        old = _write(tmp_path, "old.json", _bench(
            4.0, q1={"engine_s": 1.0, "compiles_warm": 3}))
        new = _write(tmp_path, "new.json", _bench(
            4.0, q1={"engine_s": 1.0, "compiles_warm": 0}))
        assert bench_compare.main([old, new]) == 0
        assert "improved" in capsys.readouterr().out


# ---------------------------------------------------------------------------------
# perfwatch: the append-only regression sentinel
# ---------------------------------------------------------------------------------

class TestPerfwatch:
    def _ledger(self, tmp_path):
        return str(tmp_path / "perf.jsonl")

    def test_bench_record_then_clean_check(self, tmp_path, capsys):
        led = self._ledger(tmp_path)
        base = _write(tmp_path, "b0.json", _bench(
            4.0, q1={"engine_s": 1.0, "syncs_warm": 2,
                     "compiles_warm": 0}))
        assert perfwatch.main(["record", led, base]) == 0
        run = _write(tmp_path, "b1.json", _bench(
            4.05, q1={"engine_s": 1.02, "syncs_warm": 2,
                      "compiles_warm": 0}))
        assert perfwatch.main(["check", led, run]) == 0
        assert "OK" in capsys.readouterr().out

    def test_bench_compile_and_sync_regressions_gate(self, tmp_path,
                                                     capsys):
        led = self._ledger(tmp_path)
        base = _write(tmp_path, "b0.json", _bench(
            4.0, q1={"engine_s": 1.0, "syncs_warm": 2,
                     "compiles_warm": 0}))
        assert perfwatch.main(["record", led, base]) == 0
        run = _write(tmp_path, "b1.json", _bench(
            4.0, q1={"engine_s": 1.0, "syncs_warm": 3,
                     "compiles_warm": 1}))
        assert perfwatch.main(["check", led, run]) == 1
        err = capsys.readouterr().err
        assert "compiles_warm 0 -> 1" in err
        assert "syncs_warm 2 -> 3" in err
        # the tolerances admit the same run
        assert perfwatch.main(
            ["check", led, run, "--max-sync-increase", "1",
             "--max-compile-increase", "1"]) == 0

    def _loadgen_report(self, tmp_path, name, p95, slo=0):
        return _write(tmp_path, name, {
            "loadgen": 1, "p50_ms": 10.0, "p95_ms": p95,
            "p99_ms": p95 * 1.4, "throughput_qps": 50.0,
            "typed_errors": 0, "mismatches": 0,
            "slo_violations": slo, "queries_completed": 100})

    def test_loadgen_latency_and_slo_gates(self, tmp_path, capsys):
        led = self._ledger(tmp_path)
        base = self._loadgen_report(tmp_path, "l0.json", p95=20.0)
        assert perfwatch.main(["record", led, base]) == 0
        ok = self._loadgen_report(tmp_path, "l1.json", p95=22.0)
        assert perfwatch.main(["check", led, ok]) == 0
        slow = self._loadgen_report(tmp_path, "l2.json", p95=40.0)
        assert perfwatch.main(["check", led, slow]) == 1
        assert "p95_ms" in capsys.readouterr().err
        burned = self._loadgen_report(tmp_path, "l3.json", p95=20.0,
                                      slo=3)
        assert perfwatch.main(["check", led, burned]) == 1
        assert "slo_violations 0 -> 3" in capsys.readouterr().err

    def test_check_record_appends_and_baseline_modes(self, tmp_path,
                                                     capsys):
        led = self._ledger(tmp_path)
        run = _write(tmp_path, "b.json", _bench(
            4.0, q1={"engine_s": 1.0}))
        # first check of a stream: no baseline, still exit 0
        assert perfwatch.main(["check", led, run, "--record"]) == 0
        assert "no baseline" in capsys.readouterr().out
        assert len(perfwatch.read_ledger(led)) == 1
        for mode in ("last", "best", "median"):
            assert perfwatch.main(
                ["check", led, run, "--baseline", mode]) == 0
        assert perfwatch.main(["show", led]) == 0
        assert "1 run(s)" in capsys.readouterr().out

    def test_labels_partition_streams(self, tmp_path, capsys):
        led = self._ledger(tmp_path)
        a = _write(tmp_path, "a.json", _bench(4.0, q1={"engine_s": 1.0}))
        assert perfwatch.main(["record", led, a, "--label", "tpch"]) == 0
        slow = _write(tmp_path, "s.json", _bench(
            4.0, q1={"engine_s": 9.0}))
        # a different label never gates against the tpch stream
        assert perfwatch.main(
            ["check", led, slow, "--label", "tpcds"]) == 0
        assert perfwatch.main(
            ["check", led, slow, "--label", "tpch"]) == 1
        capsys.readouterr()

    def test_usage_and_parse_errors(self, tmp_path, capsys):
        led = self._ledger(tmp_path)
        assert perfwatch.main(["check", led]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert perfwatch.main(["record", led, str(bad)]) == 2
        capsys.readouterr()
        # a torn ledger line is skipped, not fatal
        run = _write(tmp_path, "ok.json", _bench(
            4.0, q1={"engine_s": 1.0}))
        assert perfwatch.main(["record", led, run]) == 0
        with open(led, "a") as f:
            f.write("{torn json\n")
        assert perfwatch.main(["check", led, run]) == 0
        capsys.readouterr()


# ---------------------------------------------------------------------------------
# /debug/slow + srtop slow-queries panel
# ---------------------------------------------------------------------------------

class TestDebugSlowSurfaces:
    def test_render_debug_slow_lists_captures_and_ledger(
            self, fresh_recorder, tmp_path):
        from spark_rapids_tpu.server.ops import render_debug_slow
        cap = _sealed_capture(fresh_recorder, tmp_path)
        recorder.compile_note(0.2, "stmt:hot")
        page = render_debug_slow()
        assert "flight recorder:" in page
        assert cap.capture_id in page
        assert "compile" in page  # the verdict column
        assert "compile ledger:" in page
        assert "stmt:hot" in page
        assert "first_seen=1" in page

    def test_http_route_and_snapshot_section(self, sess,
                                             fresh_recorder, tmp_path):
        import urllib.request

        from spark_rapids_tpu.server import SqlFrontDoor
        cap = _sealed_capture(fresh_recorder, tmp_path)
        door = SqlFrontDoor(sess).start()
        try:
            base = f"http://127.0.0.1:{door.ops_port}"
            with urllib.request.urlopen(base + "/debug/slow",
                                        timeout=5) as r:
                assert r.status == 200
                body = r.read().decode()
            assert cap.capture_id in body
            with urllib.request.urlopen(base + "/snapshot",
                                        timeout=5) as r:
                snap = json.loads(r.read().decode())
            rec = snap["recorder"]
            assert rec["queries"] >= 1
            assert rec["captures"][0]["capture_id"] == cap.capture_id
            assert "compile_ledger" in rec
        finally:
            door.close()

    def test_srtop_slow_queries_panel(self, sess, fresh_recorder,
                                      tmp_path, capsys):
        from spark_rapids_tpu.server import SqlFrontDoor

        import tools.srtop as srtop
        cap = _sealed_capture(fresh_recorder, tmp_path)
        door = SqlFrontDoor(sess).start()
        try:
            rc = srtop.main(["--url",
                             f"http://127.0.0.1:{door.ops_port}",
                             "--once"])
        finally:
            door.close()
        assert rc == 0
        out = capsys.readouterr().out
        assert "recorder:" in out
        assert "slow queries (fingerprint / wall / why / capture):" \
            in out
        assert cap.capture_id in out
        assert "compile" in out

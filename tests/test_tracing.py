"""Structured query tracing: span trees, profiled EXPLAIN, trace export.

Covers the acceptance surface of the tracing layer (ISSUE 2): the span
tree mirrors the physical plan, the Chrome-trace JSON round-trips and
validates as trace events, profiled explain carries rows/bytes/time for
every operator, the tracing-off path stays on the fast path, and
QueryStats is query-scoped (concurrent queries don't cross-account).
"""

import json
import threading

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F

TRACE_KEY = "spark.rapids.tpu.sql.trace.enabled"
DIR_KEY = "spark.rapids.tpu.sql.trace.dir"
RECORDER_KEY = "spark.rapids.tpu.recorder.enabled"


@pytest.fixture()
def sess():
    s = srt.Session.get_or_create()
    yield s
    s.conf.unset(TRACE_KEY)
    s.conf.unset(DIR_KEY)
    s.conf.unset(RECORDER_KEY)


def _tpch_slice(sess, n=20000, seed=11):
    """A Q6/Q1-flavored slice: scan -> filter -> grouped agg."""
    rng = np.random.default_rng(seed)
    df = sess.create_dataframe({
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": (rng.random(n) * 100000).round(2),
        "l_discount": rng.integers(0, 11, n).astype(np.float64) / 100,
    })
    return (df.where((F.col("l_discount") >= 0.05)
                     & (F.col("l_quantity") < 24))
            .group_by((F.col("l_quantity") % 4).cast("int").alias("b"))
            .agg(F.sum(F.col("l_extendedprice")).alias("rev"),
                 F.count_star().alias("n")))


def _run_traced(sess, q):
    sess.conf.set(TRACE_KEY, True)
    try:
        q.collect()
    finally:
        sess.conf.unset(TRACE_KEY)
    tr = sess.last_trace()
    assert tr is not None
    return tr


# ---------------------------------------------------------------------------------
# span tree structure
# ---------------------------------------------------------------------------------

def test_span_tree_matches_physical_plan(sess):
    tr = _run_traced(sess, _tpch_slice(sess))
    phys = sess._last_phys

    def plan_shape(node):
        return (node.op_id, type(node).__name__,
                [plan_shape(c) for c in node.children])

    def tree_shape(entry):
        return (entry["op_id"], entry["name"],
                [tree_shape(c) for c in entry["children"]])

    # the first root IS the plan; extra roots (if any) are runtime ops
    assert tree_shape(tr.roots[0]) == plan_shape(phys)
    # every plan operator produced at least one operator span event
    op_ids_with_events = {e[0] for e in tr.events if e[2] == "operator"}

    def walk_ids(node):
        yield node.op_id
        for c in node.children:
            yield from walk_ids(c)

    for op_id in walk_ids(phys):
        assert op_id in op_ids_with_events, f"no operator span for {op_id}"


def test_span_tree_carries_operator_metrics(sess):
    tr = _run_traced(sess, _tpch_slice(sess))

    def walk(entry):
        yield entry
        for c in entry["children"]:
            yield from walk(c)

    for entry in walk(tr.roots[0]):
        m = entry["metrics"]
        assert m.get("outputRows", 0) > 0, entry["op_id"]
        assert m.get("outputBatches", 0) >= 1
        assert m.get("produceTimeS", 0) > 0
    # the absorbed QueryStats snapshot rides on the root attrs
    assert "blocking_fetches" in tr.attrs
    assert "compiles" in tr.attrs


# ---------------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------------

def test_trace_json_roundtrips_and_validates(sess):
    tr = _run_traced(sess, _tpch_slice(sess))
    data = json.loads(json.dumps(tr.to_chrome()))
    evs = data["traceEvents"]
    assert evs, "no trace events"
    cats = set()
    for e in evs:
        assert e["ph"] in ("X", "M", "i")
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
            cats.add(e.get("cat"))
    # the phases the span model promises
    assert "query" in cats and "operator" in cats and "phase" in cats
    assert "fetch" in cats
    # the query-level event spans the run and carries the stats snapshot
    q = next(e for e in evs if e.get("cat") == "query")
    assert q["dur"] > 0 and q["args"]["blocking_fetches"] >= 1
    # every operator event fits inside the query window (with slack for
    # float rounding)
    for e in evs:
        if e.get("cat") == "operator":
            assert e["ts"] + e["dur"] <= q["dur"] * 1.05 + 1000


def test_trace_dir_writes_one_file_per_query(sess, tmp_path):
    sess.conf.set(TRACE_KEY, True)
    sess.conf.set(DIR_KEY, str(tmp_path))
    try:
        _tpch_slice(sess).collect()
        _tpch_slice(sess, seed=12).collect()
    finally:
        sess.conf.unset(TRACE_KEY)
        sess.conf.unset(DIR_KEY)
    # the every-query dump writes query-*.trace.json; the flight
    # recorder dumps what retention keeps as capture-*.trace.json
    # into the same dir (tested in test_recorder.py)
    files = sorted(p for p in tmp_path.glob("*.trace.json")
                   if not p.name.startswith("capture-"))
    assert len(files) == 2
    for f in files:
        data = json.loads(f.read_text())
        assert data["traceEvents"]
        assert data["spanTree"]


# ---------------------------------------------------------------------------------
# profiled EXPLAIN
# ---------------------------------------------------------------------------------

def test_profiled_explain_annotates_every_operator(sess):
    q = _tpch_slice(sess)
    out = q.explain_profiled()
    phys = sess._last_phys
    n_ops = 0

    def walk(node):
        nonlocal n_ops
        n_ops += 1
        for c in node.children:
            walk(c)

    walk(phys)
    # one metrics line per operator, each with rows/bytes/time
    metric_lines = [ln for ln in out.splitlines() if "rows=" in ln]
    assert len(metric_lines) >= n_ops
    annotated = [ln for ln in metric_lines if "(not executed)" not in ln]
    assert len(annotated) >= n_ops
    for ln in annotated:
        assert "bytes=" in ln and "time=" in ln and "batches=" in ln
    # the tree itself is rendered too
    assert "TpuScan" in out and "TpuHashAggregate" in out


def test_profiled_explain_mode_prints(sess, capsys):
    _tpch_slice(sess).explain("profiled")
    out = capsys.readouterr().out
    assert "rows=" in out and "TpuScan" in out


def test_profiled_explain_without_query(fresh_session):
    assert "no query" in fresh_session.profiled_explain()


# ---------------------------------------------------------------------------------
# tracing-off fast path
# ---------------------------------------------------------------------------------

def test_tracing_off_stays_on_fast_path(fresh_session):
    from spark_rapids_tpu.utils import tracing
    # the flight recorder (default on) arms tracing for every query;
    # this test is about the FULLY-off fast path, so disarm it too
    fresh_session.conf.set(RECORDER_KEY, False)
    q = _tpch_slice(fresh_session)
    assert tracing.active() is None
    q.collect()
    # no trace captured, no active trace leaked
    assert fresh_session.last_trace() is None
    assert tracing.active() is None
    # the off-path primitives are allocation-free no-ops
    assert tracing.span("x", "y") is tracing.NULL_SPAN
    tracing.record("x", "y", "phase", 0.0, 1.0)  # no-op, no error
    tracing.mark("x", "y")


def test_trace_scope_does_not_leak_across_queries(sess):
    # disarm the recorder: with it on, every query is traced (by
    # design) and last_trace legitimately moves on
    sess.conf.set(RECORDER_KEY, False)
    tr1 = _run_traced(sess, _tpch_slice(sess))
    # an untraced query afterwards must not disturb the captured trace
    _tpch_slice(sess, seed=13).collect()
    assert sess.last_trace() is tr1
    n_events = len(tr1.events)
    _tpch_slice(sess, seed=14).collect()
    assert len(tr1.events) == n_events


def test_trace_spans_cross_pipeline_threads(sess):
    """With the async pipeline on, worker threads run in a copied context
    and their stage/wait spans join the query's trace."""
    sess.conf.set("spark.rapids.tpu.sql.pipeline.depth", 2)
    sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 4096)
    try:
        tr = _run_traced(sess, _tpch_slice(sess, n=30000))
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.pipeline.depth")
        sess.conf.unset("spark.rapids.tpu.sql.batchSizeRows")
    cats = {e[2] for e in tr.events}
    assert "pipeline" in cats, "worker-thread spans missing from trace"
    # events landed on more than one thread lane and each lane is named
    tids = {e[5] for e in tr.events}
    assert len(tids) > 1
    names = [e for e in tr.to_chrome()["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert any("pipeline" in e["args"]["name"] for e in names)
    # the query-scoped stats saw the pipeline accounting
    assert tr.attrs.get("pipeline_stage_s", 0) > 0


def test_trace_event_cap_drops_not_grows(sess):
    sess.conf.set(TRACE_KEY, True)
    sess.conf.set("spark.rapids.tpu.sql.trace.maxEvents", 5)
    try:
        _tpch_slice(sess).collect()
    finally:
        sess.conf.unset(TRACE_KEY)
        sess.conf.unset("spark.rapids.tpu.sql.trace.maxEvents")
    tr = sess.last_trace()
    # at most maxEvents stored + the ONE forced trace:events_dropped
    # mark (the only event allowed past the cap): a truncated trace is
    # visibly truncated on the timeline, not just in otherData
    assert len(tr.events) <= 5 + 1
    assert tr.dropped > 0
    marks = [e for e in tr.events if e[1] == "trace:events_dropped"]
    assert len(marks) == 1
    assert marks[0][6]["max_events"] == 5
    assert tr.to_chrome()["otherData"]["dropped_events"] == tr.dropped


# ---------------------------------------------------------------------------------
# QueryStats scoping (contextvars)
# ---------------------------------------------------------------------------------

def test_querystats_scoped_concurrent_queries():
    import jax.numpy as jnp

    from spark_rapids_tpu.utils.metrics import QueryStats, fetch

    before = QueryStats.process().blocking_fetches
    counts = {}
    barrier = threading.Barrier(2)

    def worker(name, n):
        with QueryStats.scoped() as s:
            barrier.wait(timeout=10)
            for _ in range(n):
                fetch(jnp.ones((8,)))
            counts[name] = s.blocking_fetches

    t1 = threading.Thread(target=worker, args=("a", 3))
    t2 = threading.Thread(target=worker, args=("b", 5))
    t1.start(); t2.start(); t1.join(); t2.join()
    # each scope saw exactly its own fetches — no cross-accounting
    assert counts == {"a": 3, "b": 5}
    # and the process aggregate kept the cumulative total
    assert QueryStats.process().blocking_fetches == before + 8


def test_querystats_scope_folds_into_process():
    import jax.numpy as jnp

    from spark_rapids_tpu.utils.metrics import QueryStats, fetch

    before = QueryStats.process().snapshot()
    with QueryStats.scoped() as s:
        fetch(jnp.arange(4))
        assert s.blocking_fetches == 1
        assert QueryStats.get() is s
    after = QueryStats.process().snapshot()
    assert after["blocking_fetches"] == before["blocking_fetches"] + 1
    assert after["fetch_bytes"] > before["fetch_bytes"]
    assert QueryStats.get() is QueryStats.process()


def test_querystats_nested_scopes_fold_outward():
    import jax.numpy as jnp

    from spark_rapids_tpu.utils.metrics import QueryStats, fetch

    with QueryStats.scoped() as outer:
        with QueryStats.scoped() as inner:
            fetch(jnp.arange(4))
            assert inner.blocking_fetches == 1
            assert outer.blocking_fetches == 0
        assert outer.blocking_fetches == 1


# ---------------------------------------------------------------------------------
# SYNC_TRACE cap
# ---------------------------------------------------------------------------------

def test_sync_trace_capped(monkeypatch):
    import jax.numpy as jnp

    from spark_rapids_tpu.utils import metrics as M

    monkeypatch.setattr(M, "_TRACE_SYNCS", True)
    monkeypatch.setattr(M, "SYNC_TRACE_MAX", 3)
    monkeypatch.setattr(M, "SYNC_TRACE", [])
    monkeypatch.setattr(M, "_SYNC_TRACE_DROPPED", [0])
    for _ in range(7):
        M.fetch(jnp.arange(4))
    assert len(M.SYNC_TRACE) == 3
    assert M.sync_trace_dropped() == 4

"""The minimum end-to-end slice: TPC-H Q6 shape over parquet
(BASELINE.json configs[0]; SURVEY.md §7.2 step 4)."""

import datetime

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest


def _make_lineitem(tmp_path, n=20000, seed=7):
    rng = np.random.default_rng(seed)
    tbl = pa.table({
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": (rng.random(n) * 100000).round(2),
        "l_discount": rng.integers(0, 11, n).astype(np.float64) / 100,
        "l_shipdate": pa.array(
            np.datetime64("1992-01-01")
            + rng.integers(0, 2500, n).astype("timedelta64[D]"),
            type=pa.date32()),
    })
    path = str(tmp_path / "lineitem.parquet")
    pq.write_table(tbl, path)
    return path, tbl.to_pandas()


def test_q6(session, tmp_path):
    from spark_rapids_tpu.sql import functions as F
    path, pdf = _make_lineitem(tmp_path)
    df = session.read_parquet(path)
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    out = (df.where((F.col("l_shipdate") >= lo) & (F.col("l_shipdate") < hi)
                    & (F.col("l_discount") >= 0.05)
                    & (F.col("l_discount") <= 0.07)
                    & (F.col("l_quantity") < 24))
             .agg(F.sum(F.col("l_extendedprice") * F.col("l_discount"))
                  .alias("revenue"))).collect()
    m = ((pdf.l_shipdate >= lo) & (pdf.l_shipdate < hi)
         & (pdf.l_discount >= 0.05) & (pdf.l_discount <= 0.07)
         & (pdf.l_quantity < 24))
    expected = float((pdf.l_extendedprice[m] * pdf.l_discount[m]).sum())
    assert out[0][0] == pytest.approx(expected, rel=1e-12)


def test_q6_multi_batch(session, tmp_path):
    """Same query with small batches: exercises the concat-merge agg loop."""
    from spark_rapids_tpu.sql import functions as F
    path, pdf = _make_lineitem(tmp_path, n=30000)
    session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 4096)
    try:
        df = session.read_parquet(path)
        lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
        out = (df.where((F.col("l_shipdate") >= lo)
                        & (F.col("l_shipdate") < hi)
                        & (F.col("l_quantity") < 24))
                 .group_by((F.col("l_quantity") % 3).cast("int").alias("b"))
                 .agg(F.sum(F.col("l_extendedprice")).alias("s"),
                      F.count_star().alias("c"))).collect()
    finally:
        session.conf.unset("spark.rapids.tpu.sql.batchSizeRows")
    m = ((pdf.l_shipdate >= lo) & (pdf.l_shipdate < hi) & (pdf.l_quantity < 24))
    sub = pdf[m]
    exp = sub.groupby((sub.l_quantity % 3).astype("int32")).agg(
        s=("l_extendedprice", "sum"), c=("l_quantity", "size"))
    got = {b: (s, c) for b, s, c in out}
    for b, row in exp.iterrows():
        assert got[b][1] == row.c
        assert got[b][0] == pytest.approx(row.s, rel=1e-12)


def test_explain_shows_placement(session, tmp_path):
    from spark_rapids_tpu.sql import functions as F
    path, _ = _make_lineitem(tmp_path, n=1000)
    df = session.read_parquet(path)
    s = df.where(F.col("l_quantity") < 24).explain_string()
    assert "runs on TPU" in s
    assert "Scan parquet" in s

"""UDF compiler: Python AST -> device expression trees (udf-compiler module
analog — LambdaReflection/CatalystExpressionBuilder for JVM bytecode)."""

import math

import numpy as np
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def _all_tpu(df):
    plan = df.explain_string()
    return not any(ln.strip().startswith("!") for ln in plan.splitlines()[2:])


def test_arith_lambda_compiles_to_device(session):
    f = F()
    fn = f.udf(lambda x: x * 2 + 1)
    df = session.create_dataframe({"x": [1.0, 2.0, None]})
    q = df.select(fn(f.col("x")).alias("y"))
    assert _all_tpu(q), q.explain_string()
    assert [r[0] for r in q.collect()] == [3.0, 5.0, None]


def test_conditional_and_null_check(session):
    from spark_rapids_tpu import types as T
    f = F()
    fn = f.udf(lambda a, b: None if a is None or b is None else a * 10 + b,
               return_type=T.INT64)
    df = session.create_dataframe({"a": [1, 2, None], "b": [5, None, 7]})
    q = df.select(fn(f.col("a"), f.col("b")).alias("c"))
    assert _all_tpu(q), q.explain_string()
    assert [r[0] for r in q.collect()] == [15, None, None]


def test_def_function_with_branches(session):
    f = F()

    @f.udf
    def relu6(x):
        if x < 0:
            return 0.0
        if x > 6:
            return 6.0
        return x

    df = session.create_dataframe({"x": [-2.0, 3.0, 9.0]})
    q = df.select(relu6(f.col("x")).alias("y"))
    assert _all_tpu(q), q.explain_string()
    assert [r[0] for r in q.collect()] == [0.0, 3.0, 6.0]


def test_math_whitelist_and_locals(session):
    f = F()

    @f.udf
    def gauss(x):
        z = (x - 1.0) / 2.0
        return math.exp(-z * z / 2.0) / math.sqrt(2.0 * math.pi)

    df = session.create_dataframe({"x": [0.0, 1.0, 2.0]})
    q = df.select(gauss(f.col("x")).alias("g"))
    assert _all_tpu(q), q.explain_string()
    got = [r[0] for r in q.collect()]
    exp = [math.exp(-(((x - 1) / 2) ** 2) / 2) / math.sqrt(2 * math.pi)
           for x in [0.0, 1.0, 2.0]]
    np.testing.assert_allclose(got, exp, rtol=1e-12)


def test_closure_constant_capture(session):
    f = F()
    scale = 2.5
    fn = f.udf(lambda x: x * scale)
    df = session.create_dataframe({"x": [2.0, 4.0]})
    q = df.select(fn(f.col("x")).alias("y"))
    assert _all_tpu(q)
    assert [r[0] for r in q.collect()] == [5.0, 10.0]


def test_uncompilable_falls_back_to_cpu(session):
    f = F()
    fn = f.udf(lambda x: int(str(int(x))[::-1]),
               return_type=__import__("spark_rapids_tpu").types.INT64)
    df = session.create_dataframe({"x": [123.0, 450.0]})
    q = df.select(fn(f.col("x")).alias("r"))
    assert not _all_tpu(q)  # row-wise CPU UDF with explain reason
    assert [r[0] for r in q.collect()] == [321, 54]


def test_compile_udf_direct():
    from spark_rapids_tpu.udf_compiler import UdfCompileError, compile_udf
    from spark_rapids_tpu import exprs as E
    x = E.UnresolvedColumn("x")
    e = compile_udf(lambda x: abs(x) if x != 0 else 1.0, [x])
    assert isinstance(e, E.If)
    with pytest.raises(UdfCompileError):
        compile_udf(lambda x: [x], [x])
    with pytest.raises(UdfCompileError):
        compile_udf(lambda x: x.upper(), [x])


def test_min_max_in_chained_compare(session):
    f = F()
    fn = f.udf(lambda a, b: min(a, b) if 0 < a < 10 else max(a, b))
    df = session.create_dataframe({"a": [5.0, 20.0], "b": [7.0, 3.0]})
    q = df.select(fn(f.col("a"), f.col("b")).alias("y"))
    assert _all_tpu(q)
    assert [r[0] for r in q.collect()] == [5.0, 20.0]

"""monotonically_increasing_id / spark_partition_id / input_file_name
(GpuMonotonicallyIncreasingID, GpuSparkPartitionID, GpuInputFileName)
and the zero-copy device export surface (ColumnarRdd.scala:42-51
analog)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


class TestIdExpressions:
    def test_mid_unique_increasing(self, sess, rng):
        n = 5000
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1024)
        t = pa.table({"v": pa.array(rng.uniform(0, 1, n))})
        rows = (sess.create_dataframe(t)
                .select(F.monotonically_increasing_id().alias("id"),
                        F.col("v")).collect())
        ids = [r[0] for r in rows]
        assert len(set(ids)) == n
        assert ids == sorted(ids)

    def test_mid_composes_with_device_exprs(self, sess, rng):
        t = pa.table({"v": pa.array(np.arange(100, dtype=np.int64))})
        rows = (sess.create_dataframe(t)
                .select((F.monotonically_increasing_id() * 2
                         + F.col("v") * 0).alias("x")).collect())
        assert [r[0] for r in rows] == [2 * i for i in range(100)]

    def test_spark_partition_id(self, sess, rng):
        t = pa.table({"v": pa.array(np.arange(50, dtype=np.int64))})
        rows = (sess.create_dataframe(t)
                .select(F.spark_partition_id().alias("p")).collect())
        assert all(r[0] == 0 for r in rows)

    def test_input_file_name_over_scan(self, sess, tmp_path, rng):
        p = str(tmp_path / "data.parquet")
        pq.write_table(pa.table({"v": pa.array(np.arange(20))}), p)
        rows = (sess.read_parquet(p)
                .select(F.col("v"),
                        F.input_file_name().alias("f")).collect())
        assert all(r[1] == p for r in rows)

    def test_input_file_name_degrades_off_scan(self, sess, rng):
        t = pa.table({"v": pa.array(np.arange(10, dtype=np.int64))})
        g = (sess.create_dataframe(t).group_by("v")
             .agg(F.count_star().alias("c"))
             .select(F.input_file_name().alias("f")))
        assert all(r[0] == "" for r in g.collect())

    def test_filter_not_pushed_past_mid(self, sess):
        """The optimizer must not reorder filters past these
        nondeterministic expressions."""
        t = pa.table({"v": pa.array(np.arange(100, dtype=np.int64))})
        df = (sess.create_dataframe(t)
              .select(F.col("v"),
                      F.monotonically_increasing_id().alias("id"))
              .filter(F.col("id") < 10))
        rows = df.collect()
        assert sorted(r[0] for r in rows) == list(range(10))


class TestDeviceExport:
    def test_to_device_arrays_roundtrip(self, sess, rng):
        import jax.numpy as jnp
        n = 1000
        t = pa.table({"k": pa.array(rng.integers(0, 7, n)),
                      "v": pa.array(rng.uniform(0, 10, n))})
        df = (sess.create_dataframe(t).group_by("k")
              .agg(F.sum(F.col("v")).alias("s")))
        arrs = df.to_device_arrays()
        assert set(arrs) == {"k", "s"}
        data, valid = arrs["s"]
        # the arrays are live jax arrays: consume them without any host
        # conversion in between
        total = float(jnp.sum(data))
        want = t.to_pandas().groupby("k")["v"].sum().sum()
        assert abs(total - want) < 1e-9 * max(1.0, abs(want))

    def test_to_device_arrays_rejects_host_columns(self, sess):
        t = pa.table({"s": pa.array(["a", "b"])})
        with pytest.raises(TypeError, match="host-carried"):
            sess.create_dataframe(t).to_device_arrays()

    def test_to_dlpack(self, sess, rng):
        t = pa.table({"v": pa.array(rng.uniform(0, 1, 64))})
        caps = sess.create_dataframe(t).to_dlpack()
        d, v = caps["v"]
        assert "dltensor" in repr(d) or d is not None


class TestIdSplitRetry:
    def test_mid_unique_under_split_retry(self, sess, rng):
        """OOM split-and-retry halves must draw disjoint id ranges
        (unique-and-increasing is the contract; gaps are fine)."""
        n = 3000
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1024)
        sess.conf.set("spark.rapids.tpu.test.injectSplitAndRetryOOM", 1)
        try:
            t = pa.table({"v": pa.array(np.arange(n, dtype=np.int64))})
            rows = (sess.create_dataframe(t)
                    .select(F.monotonically_increasing_id().alias("id"))
                    .collect())
        finally:
            sess.conf.set("spark.rapids.tpu.test.injectSplitAndRetryOOM",
                          0)
        ids = [r[0] for r in rows]
        assert len(ids) == n
        assert len(set(ids)) == n

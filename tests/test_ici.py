"""shuffle.mode=ICI: plans execute their exchanges on the device mesh.

Differential contract: every query must produce exactly what the
single-process CACHE_ONLY engine produces (which is itself differentially
tested against pandas/duckdb elsewhere).  The suite runs on the 8-device
virtual CPU mesh the conftest forces.

Reference parity: RapidsShuffleInternalManagerBase.scala:1046 serves every
exchange in every plan; parallel/spmd.py is the TPU-native equivalent
(fragments lowered onto the mesh, SURVEY §5.8).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F


def _both_modes(df, sess):
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")
    want = df.collect()
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "ICI")
    got = df.collect()
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")
    return got, want


def _assert_rows_equal(got, want):
    def key(r):
        return tuple((x is None, x) for x in r)
    got = sorted(got, key=key)
    want = sorted(want, key=key)
    assert len(got) == len(want), f"{len(got)} vs {len(want)} rows"
    for g, w in zip(got, want):
        assert len(g) == len(w)
        for gi, wi in zip(g, w):
            if gi is None or wi is None:
                assert gi is None and wi is None, (g, w)
            elif isinstance(wi, float):
                assert abs(gi - wi) <= 1e-9 * max(1.0, abs(wi)), (g, w)
            else:
                assert gi == wi, (g, w)


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


@pytest.fixture()
def shuffle_only(sess):
    """Pin the shuffled-join path: the tiny test dims would otherwise
    auto-broadcast and bypass the all_to_all join under test."""
    sess.conf.set("spark.rapids.tpu.sql.autoBroadcastJoinThreshold", -1)
    yield sess
    sess.conf.set("spark.rapids.tpu.sql.autoBroadcastJoinThreshold",
                  10 * 1024 * 1024)


def _tables(rng, no=400, nl=2500, null_keys=False):
    ok = np.arange(no)
    lk = rng.integers(0, no + 60, nl)  # some keys match nothing
    orders = {
        "o_orderkey": pa.array(ok),
        "o_custkey": pa.array(rng.integers(0, 37, no)),
        "o_flag": pa.array(rng.integers(0, 2, no)),
    }
    items = {
        "l_orderkey": pa.array(
            [None if null_keys and i % 17 == 0 else int(v)
             for i, v in enumerate(lk)], type=pa.int64()),
        "l_price": pa.array(rng.uniform(1.0, 1000.0, nl)),
        "l_qty": pa.array(rng.integers(1, 50, nl)),
    }
    return pa.table(orders), pa.table(items)


def test_ici_grouped_agg(sess, rng):
    n = 6000
    t = pa.table({"k": pa.array(rng.integers(0, 61, n)),
                  "v": pa.array(rng.uniform(0, 100, n)),
                  "w": pa.array(rng.integers(-5, 5, n))})
    df = (sess.create_dataframe(t).group_by("k")
          .agg(F.sum(F.col("v")).alias("s"),
               F.count(F.col("v")).alias("c"),
               F.avg(F.col("v")).alias("a"),
               F.min(F.col("w")).alias("mn"),
               F.max(F.col("w")).alias("mx")))
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_string_group_keys(sess, rng):
    n = 3000
    cats = ["alpha", "beta", "gamma", "delta", None]
    t = pa.table({
        "k": pa.array([cats[i % len(cats)] for i in range(n)]),
        "v": pa.array(rng.uniform(0, 10, n))})
    df = (sess.create_dataframe(t).group_by("k")
          .agg(F.sum(F.col("v")).alias("s")))
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_ici_join_types(shuffle_only, rng, how):
    sess = shuffle_only
    orders, items = _tables(rng, null_keys=True)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    df = do.join(dl, [("o_orderkey", "l_orderkey")], how)
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_q3_shape(shuffle_only, rng):
    """join + filter + group-by + order-by: the round-2 verdict's done
    criterion for ICI (fragment = join..final-agg; sort runs above)."""
    sess = shuffle_only
    orders, items = _tables(rng)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    df = (do.join(dl, [("o_orderkey", "l_orderkey")], "inner")
          .filter(F.col("o_flag") == 1)
          .group_by("o_custkey")
          .agg(F.sum(F.col("l_price")).alias("rev"),
               F.count(F.col("l_qty")).alias("cnt"))
          .order_by(F.col("rev").desc()))
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")
    want = df.collect()
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "ICI")
    got = df.collect()
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")
    # order-by runs in the fringe: exact ordered comparison
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2], (g, w)
        assert abs(g[1] - w[1]) <= 1e-9 * max(1.0, abs(w[1]))


def test_ici_residual_condition_inner(shuffle_only, rng):
    sess = shuffle_only
    orders, items = _tables(rng)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    joined = do.join(dl, [("o_orderkey", "l_orderkey")], "inner")
    df = joined.filter(F.col("l_price") > F.col("o_custkey") * 10.0)
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_two_fragments_union(sess, rng):
    """A union of two aggregations: union is not lowerable, so each agg
    subtree runs as its own mesh fragment (multi-fragment loop)."""
    n = 2000
    t = pa.table({"k": pa.array(rng.integers(0, 11, n)),
                  "v": pa.array(rng.uniform(0, 5, n))})
    d1 = (sess.create_dataframe(t).group_by("k")
          .agg(F.sum(F.col("v")).alias("s")))
    d2 = (sess.create_dataframe(t).group_by("k")
          .agg(F.min(F.col("v")).alias("s")))
    df = d1.union(d2)
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_string_predicate_leaf(sess, rng):
    """A host-lowered string predicate below the aggregate: the stage runs
    single-process as a fragment leaf, the exchange still rides ICI."""
    n = 2000
    cats = ["BUILDING", "MACHINERY", "AUTOMOBILE"]
    t = pa.table({
        "seg": pa.array([cats[i % 3] for i in range(n)]),
        "k": pa.array(rng.integers(0, 9, n)),
        "v": pa.array(rng.uniform(0, 10, n))})
    df = (sess.create_dataframe(t)
          .filter(F.col("seg") == "BUILDING")
          .group_by("k").agg(F.sum(F.col("v")).alias("s")))
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_bucket_overflow_detected(sess, rng):
    n = 4000
    t = pa.table({"k": pa.array(rng.integers(0, 500, n)),
                  "v": pa.array(rng.uniform(0, 1, n))})
    df = (sess.create_dataframe(t).group_by("k")
          .agg(F.sum(F.col("v")).alias("s")))
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "ICI")
    sess.conf.set("spark.rapids.tpu.shuffle.ici.bucketRows", 2)
    try:
        with pytest.raises(RuntimeError, match="bucketRows"):
            df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.shuffle.ici.bucketRows", 0)
        sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")


def test_ici_bucket_overflow_transparent_recovery(sess, rng):
    """Sibling of test_ici_bucket_overflow_detected (VERDICT r4 item 8):
    a bucket one notch too small must NOT surface — distribute_plan
    re-lowers the fragment at 4x capacities and the query completes with
    answers identical to CACHE_ONLY mode."""
    n = 4000
    t = pa.table({"k": pa.array(rng.integers(0, 500, n)),
                  "v": pa.array(rng.uniform(0, 1, n))})
    df = (sess.create_dataframe(t).group_by("k")
          .agg(F.sum(F.col("v")).alias("s")))
    want = sorted(df.collect())
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "ICI")
    # ~4000/8 devices = 500 rows/device; 500 distinct keys spread over
    # 8 targets ~ 62/bucket: 32 overflows once, 128 (one 4x retry) fits
    sess.conf.set("spark.rapids.tpu.shuffle.ici.bucketRows", 32)
    try:
        got = sorted(df.collect())
    finally:
        sess.conf.set("spark.rapids.tpu.shuffle.ici.bucketRows", 0)
        sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")
    assert len(got) == len(want)
    for (gk, gs), (wk, ws) in zip(got, want):
        assert gk == wk and abs(gs - ws) < 1e-9


def test_ici_exchange_never_silently_degrades(sess):
    """An exchange reached by the single-process executor under mode=ICI
    must raise unless shuffle.ici.fallback is set (round-2 weak #2)."""
    import jax.numpy as jnp
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import Field, Schema
    from spark_rapids_tpu.exprs import BoundReference
    from spark_rapids_tpu.plan.exchange_exec import ShuffleExchangeExec
    from spark_rapids_tpu.plan.physical import ExecContext, ScanExec

    schema = Schema([Field("x", T.INT64, False)])
    scan = ScanExec(schema, lambda: iter([pa.table({"x": [1, 2, 3]})]))
    exch = ShuffleExchangeExec(
        scan, [BoundReference(0, T.INT64, False, "x")], 4)
    sess.conf.set("spark.rapids.tpu.shuffle.mode", "ICI")
    ctx = ExecContext(sess._tpu_conf(), device=sess.device)
    try:
        with pytest.raises(RuntimeError, match="ICI"):
            list(exch.execute(ctx))
        sess.conf.set("spark.rapids.tpu.shuffle.ici.fallback", True)
        ctx2 = ExecContext(sess._tpu_conf(), device=sess.device)
        outs = list(exch.execute(ctx2))
        assert sum(b.row_count() for b in outs) == 3
    finally:
        sess.conf.set("spark.rapids.tpu.shuffle.ici.fallback", False)
        sess.conf.set("spark.rapids.tpu.shuffle.mode", "CACHE_ONLY")


def test_ici_host_predicate_above_join(shuffle_only, rng):
    """A host-lowered string predicate ABOVE a shuffled join: the inner
    join fragment distributes first, then the predicate runs single-process
    and the outer aggregation distributes as a second fragment — a leaf
    must never swallow an exchange-bearing subtree."""
    sess = shuffle_only
    orders, items = _tables(rng, no=200, nl=1200)
    orders = orders.append_column(
        "o_seg", pa.array([["BUILDING", "MACHINERY"][i % 2]
                           for i in range(orders.num_rows)]))
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    df = (do.join(dl, [("o_orderkey", "l_orderkey")], "inner")
          .filter(F.col("o_seg") == "BUILDING")
          .group_by("o_custkey")
          .agg(F.sum(F.col("l_price")).alias("rev")))
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_avg_and_compound_aggs(sess, rng):
    n = 3000
    t = pa.table({"k": pa.array(rng.integers(0, 23, n)),
                  "v": pa.array(rng.uniform(0, 100, n))})
    df = (sess.create_dataframe(t).group_by("k")
          .agg((F.sum(F.col("v")) * 0.2).alias("fifth"),
               (F.max(F.col("v")) - F.min(F.col("v"))).alias("spread")))
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_ici_broadcast_join_types(sess, rng, how):
    """Broadcast joins under SPMD: the build side feeds the mesh
    replicated (P() in_spec) — no all_to_all for the join at all; the
    aggregate above still exchanges over ICI."""
    orders, items = _tables(rng, null_keys=True)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    joined = dl.join(F.broadcast(do), [("l_orderkey", "o_orderkey")], how)
    if how in ("left_semi", "left_anti"):
        df = (joined.group_by("l_qty")
              .agg(F.sum(F.col("l_price")).alias("rev")))
    else:
        df = (joined.group_by("o_custkey")
              .agg(F.sum(F.col("l_price")).alias("rev")))
    # the plan must actually contain a broadcast join
    phys = sess._plan_physical(df._plan)
    assert "TpuBroadcast" in phys.tree_string()
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


def test_ici_broadcast_right_outer(sess, rng):
    """how=right broadcasts the LEFT side (the kernel's build)."""
    orders, items = _tables(rng)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    df = (do.hint("broadcast").join(dl, [("o_orderkey", "l_orderkey")],
                                    "right")
          .group_by("l_qty")
          .agg(F.count(F.col("l_price")).alias("c")))
    phys = sess._plan_physical(df._plan)
    assert "build=left" in phys.tree_string()
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)


@pytest.mark.parametrize("how", ["left", "left_semi", "left_anti", "full"])
def test_ici_conditioned_noninner_join(shuffle_only, rng, how):
    """ADVICE r3 high: non-inner joins with a residual condition must NOT
    lower onto the mesh (the post-expansion filter is inner-only
    semantics); they run single-process via _conditioned_probe_join while
    the child exchanges still distribute."""
    sess = shuffle_only
    orders, items = _tables(rng, null_keys=True)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    joined = do.join(dl, [("o_orderkey", "l_orderkey")], how)
    joined._plan.condition = (F.col("o_custkey") * 30.0
                              < F.col("l_price")).expr
    got, want = _both_modes(joined, sess)
    _assert_rows_equal(got, want)


@pytest.mark.parametrize("how", ["left", "left_semi", "left_anti"])
def test_ici_conditioned_broadcast_noninner(sess, rng, how):
    """Same contract for broadcast joins with residual conditions."""
    orders, items = _tables(rng, null_keys=True)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    joined = dl.join(F.broadcast(do), [("l_orderkey", "o_orderkey")], how)
    joined._plan.condition = (F.col("o_custkey") * 30.0
                              < F.col("l_price")).expr
    got, want = _both_modes(joined, sess)
    _assert_rows_equal(got, want)


def test_ici_existence_join_runs_single_process(shuffle_only, rng):
    """Existence joins (IN-subquery inside OR) have no SPMD lowering —
    they must run single-process under shuffle.mode=ICI with correct
    results."""
    sess = shuffle_only
    orders, items = _tables(rng)
    do = sess.create_dataframe(orders)
    dl = sess.create_dataframe(items)
    sub = do.filter(F.col("o_flag") == 1).select("o_orderkey")
    df = dl.filter(F.col("l_orderkey").isin_subquery(sub)
                   | (F.col("l_price") > 900.0))
    got, want = _both_modes(df, sess)
    _assert_rows_equal(got, want)

"""Query service tests: scheduler, admission control, deadlines,
cancellation (spark_rapids_tpu/service/).

The contracts under test:
  (a) concurrent TPC-H slices return correct, ISOLATED results — and
      per-query QueryStats sums reconcile with the process aggregate
      (zero cross-query accounting bleed);
  (b) priority ordering is honored; a full admission queue sheds with a
      typed QueryRejected;
  (c) cancellation mid-pipeline leaks no spill handles or semaphore
      permits (SpillCatalog.assert_no_leaks) and the trace ends with a
      cancelled span status;
  (d) deadline expiry aborts a long scan (collect(timeout=) and the
      scheduler.deadlineMs conf).
"""

import threading
import time

import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.service import (QueryCancelled, QueryControl,
                                      QueryDeadlineExceeded, QueryRejected,
                                      QueryScheduler)
from spark_rapids_tpu.sql import functions as F

SLICE = ["q1", "q3", "q6", "q13"]


@pytest.fixture(scope="module")
def tpch(session, tmp_path_factory):
    from spark_rapids_tpu.models import tpch_suite
    out = str(tmp_path_factory.mktemp("tpch_sched"))
    return tpch_suite.load_db(session, 0.002, out)


def _slow_df(sess, n_batches=100, rows=512, delay=0.02):
    """A DataFrame over a scan whose decode is slow — cancellation and
    deadlines land mid-scan at a batch boundary."""
    from spark_rapids_tpu.batch import Field, Schema, _arrow_to_logical
    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.sql.dataframe import DataFrame
    tbl = pa.table({"k": [0], "v": [0.0]})
    schema = Schema([Field(n, _arrow_to_logical(t), True)
                     for n, t in zip(tbl.column_names, tbl.schema.types)])

    def factory():
        for _ in range(n_batches):
            time.sleep(delay)
            yield pa.table({"k": [j % 7 for j in range(rows)],
                            "v": [float(j) for j in range(rows)]})

    node = L.LogicalScan(schema, factory, "slow-source", fmt="memory")
    return DataFrame(node, sess)


# ---------------------------------------------------------------------------
# (a) concurrent correctness + isolation
# ---------------------------------------------------------------------------

def test_concurrent_tpch_isolated(session, tpch):
    from spark_rapids_tpu.models import tpch_suite
    from spark_rapids_tpu.utils.metrics import QueryStats
    serial = {}
    for name in SLICE:
        runner, _ = tpch_suite.QUERIES[name]
        serial[name] = runner(tpch)
    session.conf.set("spark.rapids.tpu.sql.scheduler.maxConcurrent", 3)
    try:
        stats0 = QueryStats.get().snapshot()
        handles = {
            name: session.submit(
                (lambda r=tpch_suite.QUERIES[name][0]: r(tpch)),
                label=name)
            for name in SLICE}
        results = {n: h.result(timeout=120) for n, h in handles.items()}
        delta = QueryStats.delta_since(stats0)
    finally:
        session.conf.unset("spark.rapids.tpu.sql.scheduler.maxConcurrent")
    for name in SLICE:
        assert handles[name].status == "done"
        assert tpch_suite.rows_rel_err(results[name], serial[name]) < 1e-6, \
            f"{name} diverged under concurrency"
    # per-query scopes fold into the process aggregate: the sums must
    # reconcile exactly or accounting bled across queries
    for key in ("blocking_fetches", "async_fetches", "fetch_bytes"):
        total = sum(h.stats[key] for h in handles.values())
        assert total == delta[key], \
            f"{key}: per-query sum {total} != process delta {delta[key]}"
    for h in handles.values():
        assert h.latency_s is not None and h.latency_s >= 0
        assert h.stats["queue_wait_s"] >= 0


# ---------------------------------------------------------------------------
# (b) priority ordering + overload shedding
# ---------------------------------------------------------------------------

def test_priority_ordering():
    sched = QueryScheduler(settings={
        "spark.rapids.tpu.sql.scheduler.maxConcurrent": 1,
        "spark.rapids.tpu.sql.scheduler.queueDepth": 8})
    try:
        gate = threading.Event()
        order = []
        blocker = sched.submit(lambda: gate.wait(10), label="blocker")
        while sched.running() == 0:
            time.sleep(0.005)
        lo = sched.submit(lambda: order.append("lo"), priority=0)
        hi = sched.submit(lambda: order.append("hi"), priority=5)
        gate.set()
        blocker.result(10)
        lo.result(10)
        hi.result(10)
        assert order == ["hi", "lo"], \
            f"priority ordering violated: {order}"
    finally:
        sched.close()


def test_queue_full_sheds_with_queryrejected():
    sched = QueryScheduler(settings={
        "spark.rapids.tpu.sql.scheduler.maxConcurrent": 1,
        "spark.rapids.tpu.sql.scheduler.queueDepth": 1})
    try:
        gate = threading.Event()
        blocker = sched.submit(lambda: gate.wait(10), label="blocker")
        while sched.running() == 0:
            time.sleep(0.005)
        queued = sched.submit(lambda: "q", label="queued")
        with pytest.raises(QueryRejected, match="queue full"):
            sched.submit(lambda: "shed", label="shed")
        assert sched.snapshot()["rejected"] == 1
        gate.set()
        assert queued.result(10) == "q"
        blocker.result(10)
    finally:
        sched.close()


def test_weighted_fair_tenants():
    """At equal priority, the tenant with LESS accumulated service (per
    unit weight) dispatches first."""
    sched = QueryScheduler(settings={
        "spark.rapids.tpu.sql.scheduler.maxConcurrent": 1})
    try:
        gate = threading.Event()
        order = []
        blocker = sched.submit(lambda: gate.wait(10), tenant="greedy")
        while sched.running() == 0:
            time.sleep(0.005)
        # pre-charge 'greedy' with virtual time, as if it had already
        # consumed service
        with sched._cv:
            sched._vtime["greedy"] = 10.0
        a = sched.submit(lambda: order.append("greedy"), tenant="greedy")
        b = sched.submit(lambda: order.append("fresh"), tenant="fresh")
        gate.set()
        blocker.result(10)
        a.result(10)
        b.result(10)
        assert order == ["fresh", "greedy"]
    finally:
        sched.close()


def test_cancel_queued_entry():
    sched = QueryScheduler(settings={
        "spark.rapids.tpu.sql.scheduler.maxConcurrent": 1})
    try:
        gate = threading.Event()
        blocker = sched.submit(lambda: gate.wait(10))
        while sched.running() == 0:
            time.sleep(0.005)
        queued = sched.submit(lambda: "never")
        assert queued.cancel("test") is True
        assert queued.status == "cancelled"
        with pytest.raises(QueryCancelled):
            queued.result(5)
        gate.set()
        blocker.result(10)
    finally:
        sched.close()


def test_closed_scheduler_rejects():
    sched = QueryScheduler()
    sched.close()
    with pytest.raises(QueryRejected, match="closed"):
        sched.submit(lambda: 1)


# ---------------------------------------------------------------------------
# (c) cancellation mid-pipeline: no leaked permits/handles, trace status
# ---------------------------------------------------------------------------

def test_cancel_mid_query_releases_everything(session):
    from spark_rapids_tpu.memory.spill import get_catalog
    from spark_rapids_tpu.runtime.semaphore import get_semaphore
    session.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    # force partial/exchange/final aggregation so the exchange registers
    # spillable staging handles the abort must release
    session.conf.set("spark.rapids.tpu.sql.agg.singleProcessComplete",
                     False)
    try:
        df = _slow_df(session)
        agg = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
        h = session.submit(agg, label="to-cancel")
        deadline = time.time() + 10
        while h.status == "queued" and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # let it get into the scan
        assert h.cancel("test cancellation") is True
        with pytest.raises(QueryCancelled):
            h.result(timeout=30)
        assert h.status == "cancelled"
        conf = session._tpu_conf()
        catalog = get_catalog(conf)
        catalog.assert_no_leaks()
        sem = get_semaphore(conf)
        assert sem.available() == sem.permits, \
            "cancelled query leaked semaphore permits"
        tr = h.trace()
        assert tr is not None and tr.status == "cancelled"
        assert tr.to_chrome()["otherData"]["status"] == "cancelled"
    finally:
        session.conf.unset("spark.rapids.tpu.sql.trace.enabled")
        session.conf.unset(
            "spark.rapids.tpu.sql.agg.singleProcessComplete")


# ---------------------------------------------------------------------------
# (d) deadlines abort a long scan
# ---------------------------------------------------------------------------

def test_collect_timeout_aborts_long_scan(session):
    from spark_rapids_tpu.memory.spill import get_catalog
    df = _slow_df(session)
    t0 = time.time()
    with pytest.raises(QueryDeadlineExceeded):
        df.collect(timeout=0.3)
    # cooperative: lands at the next batch boundary, far before the
    # ~2 s the full scan would take
    assert time.time() - t0 < 1.5
    get_catalog(session._tpu_conf()).assert_no_leaks()


def test_conf_deadline_aborts(session):
    session.conf.set("spark.rapids.tpu.sql.scheduler.deadlineMs", 300)
    try:
        df = _slow_df(session)
        with pytest.raises(QueryDeadlineExceeded):
            df.collect()
    finally:
        session.conf.unset("spark.rapids.tpu.sql.scheduler.deadlineMs")


def test_deadline_trace_status(session):
    session.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    try:
        df = _slow_df(session)
        with pytest.raises(QueryDeadlineExceeded):
            df.collect(timeout=0.3)
        tr = session.last_trace()
        assert tr is not None and tr.status == "deadline"
    finally:
        session.conf.unset("spark.rapids.tpu.sql.trace.enabled")


def test_scheduler_deadline_status(session):
    df = _slow_df(session)
    h = session.submit(df, deadline_s=0.3, label="deadline-query")
    with pytest.raises(QueryDeadlineExceeded):
        h.result(timeout=30)
    assert h.status == "deadline"


# ---------------------------------------------------------------------------
# control primitives
# ---------------------------------------------------------------------------

def test_query_control_wakers_and_check():
    from spark_rapids_tpu.service import cancel
    ctl = QueryControl(label="t")
    fired = []
    tok = ctl.add_waker(lambda: fired.append(1))
    assert ctl.status == "ok"
    ctl.check()  # no-op while live
    assert ctl.cancel("stop") is True
    assert fired == [1]
    assert ctl.cancel("again") is False  # idempotent
    assert ctl.status == "cancelled"
    with pytest.raises(QueryCancelled):
        ctl.check()
    ctl.remove_waker(tok)
    # a waker added after cancellation fires immediately
    late = []
    ctl.add_waker(lambda: late.append(1))
    assert late == [1]
    # module-level check is a no-op outside any scope
    cancel.check()
    with cancel.scope(ctl):
        with pytest.raises(QueryCancelled):
            cancel.check()


def test_deadline_timer_fires_wakers():
    ev = threading.Event()
    ctl = QueryControl(label="t", deadline_s=0.15)
    ctl.add_waker(ev.set)
    from spark_rapids_tpu.service import cancel
    with cancel.scope(ctl):
        assert ev.wait(2.0), "deadline timer never fired the waker"
        assert ctl.status == "deadline"
        with pytest.raises(QueryDeadlineExceeded):
            ctl.check()


def test_semaphore_resize_in_place(session):
    from spark_rapids_tpu.runtime.semaphore import get_semaphore
    conf = session._tpu_conf()
    sem = get_semaphore(conf)
    base = sem.permits
    try:
        sem2 = get_semaphore(conf.with_settings(
            **{"spark.rapids.tpu.sql.concurrentTpuTasks": base + 2}))
        assert sem2 is sem, "resize must keep the same instance"
        assert sem.permits == base + 2
        assert sem.available() == base + 2
    finally:
        get_semaphore(conf.with_settings(
            **{"spark.rapids.tpu.sql.concurrentTpuTasks": base}))
        assert sem.permits == base


def test_semaphore_acquire_cancellable():
    """A thread blocked on the semaphore aborts the moment its query is
    cancelled — event-driven, no poll interval."""
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    from spark_rapids_tpu.service import cancel
    sem = TpuSemaphore(1)
    ctl = QueryControl(label="blocked")
    errs = []

    def holder():
        with sem.acquire():
            release.wait(10)

    def blocked():
        with cancel.scope(ctl):
            try:
                with sem.acquire():
                    pass
            except QueryCancelled as e:
                errs.append(e)

    release = threading.Event()
    th = threading.Thread(target=holder)
    th.start()
    while sem.available() > 0:
        time.sleep(0.005)
    tb = threading.Thread(target=blocked)
    tb.start()
    time.sleep(0.1)
    ctl.cancel("stop waiting")
    tb.join(timeout=2.0)
    assert not tb.is_alive(), "cancelled acquire stayed blocked"
    assert len(errs) == 1
    release.set()
    th.join(timeout=2.0)
    assert sem.available() == 1


def test_scheduler_queue_wait_in_trace(session):
    session.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
    try:
        df = session.range(1000)
        h = session.submit(df, label="traced")
        h.result(timeout=30)
        tr = h.trace()
        assert tr is not None
        assert tr.attrs.get("scheduler_label") == "traced"
        assert "queue_wait_s" in tr.attrs
        names = {e[1] for e in tr.events}
        assert "scheduler:queue_wait" in names
    finally:
        session.conf.unset("spark.rapids.tpu.sql.trace.enabled")

"""Exchange in the plan: partial→exchange→final aggregation, shuffled
joins, and the planner-path distributed collect.

Reference: GpuShuffleExchangeExecBase.scala:266-383,
GpuShuffledHashJoinExec.scala:90."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.plan.exchange_exec import ShuffleExchangeExec
from spark_rapids_tpu.plan.overrides import apply_overrides
from spark_rapids_tpu.plan.physical import AggregateExec
from spark_rapids_tpu.sql import functions as F
from .support import assert_rows_equal


@pytest.fixture(autouse=True)
def _two_phase_agg(monkeypatch):
    """This module tests the exchange machinery itself: pin the
    partial->exchange->final shape that singleProcessComplete would
    otherwise collapse under CACHE_ONLY.  Patch the registry default so
    every session (shared or fresh) sees it."""
    import dataclasses
    from spark_rapids_tpu import config
    key = "spark.rapids.tpu.sql.agg.singleProcessComplete"
    monkeypatch.setitem(
        config.ALL_ENTRIES, key,
        dataclasses.replace(config.ALL_ENTRIES[key], default=False))
    yield


def _plan(df):
    return apply_overrides(df._plan, df.session._tpu_conf())


def _unfused(node):
    """See through the region wrapper: fusion groups execution, the
    member subtree is the plan shape these tests assert on."""
    from spark_rapids_tpu.plan.fusion import FusedRegionExec
    while isinstance(node, FusedRegionExec):
        node = node.children[0]
    return node


class TestExchangeInPlan:
    def test_grouped_agg_is_two_phase(self, session):
        df = session.create_dataframe({"k": [1, 2], "v": [1.0, 2.0]})
        q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
        phys = _plan(q)
        assert isinstance(phys, AggregateExec) and phys.mode == "final"
        exch = phys.children[0]
        assert isinstance(exch, ShuffleExchangeExec)
        partial = _unfused(exch.children[0])
        assert isinstance(partial, AggregateExec) and partial.mode == "partial"
        assert "TpuShuffleExchange" in phys.tree_string()

    def test_join_is_shuffled(self, session):
        l = session.create_dataframe({"k": [1], "a": [1.0]})
        r = session.create_dataframe({"k": [1], "b": [2.0]})
        # a tiny build side auto-broadcasts by default...
        phys = _plan(l.join(r, on="k"))
        assert "TpuBroadcast" in phys.tree_string()
        # ...and shuffles once broadcast selection is disabled
        session.conf.set(
            "spark.rapids.tpu.sql.autoBroadcastJoinThreshold", -1)
        try:
            phys = _plan(l.join(r, on="k"))
            assert all(isinstance(c, ShuffleExchangeExec)
                       for c in phys.children)
        finally:
            session.conf.set(
                "spark.rapids.tpu.sql.autoBroadcastJoinThreshold",
                10 * 1024 * 1024)

    def test_exchange_disabled_single_stream(self, fresh_session):
        fresh_session.conf.set("spark.rapids.tpu.sql.exchange.enabled", False)
        df = fresh_session.create_dataframe({"k": [1, 2], "v": [1.0, 2.0]})
        q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
        phys = _unfused(_plan(q))
        assert isinstance(phys, AggregateExec) and phys.mode == "complete"
        got = q.collect()
        assert_rows_equal(got, [(1, 1.0), (2, 2.0)])

    def test_two_phase_results_match_oracle(self, fresh_session):
        fresh_session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 128)
        fresh_session.conf.set("spark.rapids.tpu.sql.shuffle.partitions", 7)
        rng = np.random.default_rng(5)
        pdf = pd.DataFrame({
            "k": rng.integers(0, 100, 2000),
            "v": rng.uniform(-10, 10, 2000),
        })
        df = fresh_session.create_dataframe(pdf)
        got = (df.group_by("k")
                 .agg(F.sum(F.col("v")).alias("s"),
                      F.count_star().alias("c"),
                      F.min(F.col("v")).alias("mn"),
                      F.max(F.col("v")).alias("mx"),
                      F.avg(F.col("v")).alias("a")).collect())
        g = pdf.groupby("k")["v"]
        expect = [(int(k), float(s), int(c), float(mn), float(mx), float(a))
                  for k, s, c, mn, mx, a in zip(
                      g.sum().index, g.sum(), g.count(), g.min(), g.max(),
                      g.mean())]
        assert_rows_equal(got, expect, approx_float=True)

    def test_null_group_key_two_phase(self, session):
        t = pa.table({"k": pa.array([1, None, None, 2], type=pa.int64()),
                      "v": pa.array([1.0, 2.0, 3.0, 4.0])})
        got = (session.create_dataframe(t).group_by("k")
               .agg(F.sum(F.col("v")).alias("s")).collect())
        assert_rows_equal(got, [(1, 1.0), (2, 4.0), (None, 5.0)])

    def test_shuffled_join_many_partitions(self, fresh_session):
        fresh_session.conf.set("spark.rapids.tpu.sql.shuffle.partitions", 5)
        rng = np.random.default_rng(9)
        lpd = pd.DataFrame({"k": rng.integers(0, 40, 800),
                            "a": np.arange(800)})
        rpd = pd.DataFrame({"k": rng.integers(0, 40, 300),
                            "b": np.arange(300)})
        got = fresh_session.create_dataframe(lpd).join(
            fresh_session.create_dataframe(rpd), on="k", how="left").collect()
        expect = lpd.merge(rpd, on="k", how="left")
        assert len(got) == len(expect)
        s_g = sum(r[2] for r in got if r[2] is not None)
        assert s_g == int(expect["b"].dropna().sum())

    def test_mixed_type_keys_partition_consistently(self, session):
        # int32 vs int64 keys must hash to the same partition (promoted)
        lt = pa.table({"k": pa.array(range(50), type=pa.int32()),
                       "a": pa.array(range(50), type=pa.int64())})
        rt = pa.table({"k": pa.array(range(0, 100, 2), type=pa.int64()),
                       "b": pa.array(range(50), type=pa.int64())})
        got = session.create_dataframe(lt).join(
            session.create_dataframe(rt), on="k", how="inner").collect()
        assert len(got) == 25  # even keys 0..48

    def test_distinct_two_phase(self, fresh_session):
        fresh_session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 64)
        pdf = pd.DataFrame({"k": [1, 2, 1, 3, 2, 1] * 50})
        got = fresh_session.create_dataframe(pdf).distinct().collect()
        assert sorted(got) == [(1,), (2,), (3,)]


class TestDistributedPlannerPath:
    def test_distributed_agg_matches_engine(self):
        import jax
        from jax.sharding import Mesh
        from spark_rapids_tpu.parallel.distributed import (
            distributed_agg_collect)
        devices = jax.devices()[:4]
        if len(devices) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(devices), ("data",))
        rng = np.random.default_rng(3)
        rows = 4 * 512
        table = pa.table({
            "k": pa.array(rng.integers(0, 30, rows).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 50, rows)),
        })
        sess = srt.Session.get_or_create()
        df = (sess.create_dataframe(table).group_by("k")
              .agg(F.sum(F.col("v")).alias("s"),
                   F.count_star().alias("c")))
        got = distributed_agg_collect(df, mesh, table)
        want = df.collect()
        assert_rows_equal(got, want, approx_float=True)

    def test_distributed_rejects_overflow(self):
        import jax
        from jax.sharding import Mesh
        from spark_rapids_tpu.parallel.distributed import (
            distributed_agg_collect)
        devices = jax.devices()[:2]
        if len(devices) < 2:
            pytest.skip("needs 2 virtual devices")
        mesh = Mesh(np.array(devices), ("data",))
        rows = 2 * 256
        table = pa.table({
            "k": pa.array(np.arange(rows).astype(np.int64)),  # all distinct
            "v": pa.array(np.ones(rows)),
        })
        sess = srt.Session.get_or_create()
        df = (sess.create_dataframe(table).group_by("k")
              .agg(F.sum(F.col("v")).alias("s")))
        with pytest.raises(RuntimeError, match="overflow"):
            # bucket_cap=8 cannot carry 256 distinct keys per device
            distributed_agg_collect(df, mesh, table, bucket_cap=8)


def test_agg_exchange_coalesces_partitions(session, rng):
    """Final-agg exchanges merge small partitions into target-size batches
    (AQE coalesced shuffle read): far fewer output batches than partitions,
    same results."""
    from .support import DoubleGen, IntGen, gen_table
    from spark_rapids_tpu.sql import functions as f
    table, pdf = gen_table(rng, {
        "k": IntGen(lo=0, hi=200, dtype="int64", nullable=False),
        "v": DoubleGen(special=False, nullable=False)}, 3000)
    df = session.create_dataframe(table)
    q = df.group_by("k").agg(f.sum(f.col("v")).alias("s"))

    phys = session._plan_physical(q._plan)

    def find_exchange(node):
        from spark_rapids_tpu.plan.exchange_exec import ShuffleExchangeExec
        if isinstance(node, ShuffleExchangeExec):
            return node
        for c in getattr(node, "children", ()):
            r = find_exchange(c)
            if r is not None:
                return r
        return None

    ex = find_exchange(phys)
    assert ex is not None and ex.coalesce_output
    from spark_rapids_tpu.plan.physical import ExecContext
    ctx = ExecContext(session._tpu_conf(), device=session.device)
    n_batches = sum(1 for _ in ex.execute(ctx))
    assert n_batches < ex.n_parts  # small partitions merged

    got = dict(q.collect())
    exp = pdf.groupby("k")["v"].sum()
    assert len(got) == len(exp)
    for k, v in exp.items():
        assert got[int(k)] == pytest.approx(v)

"""Scalar/IN subqueries (plan/subquery.py) and dynamic partition pruning
(join_exec._inject_dpp).  Reference: GpuScalarSubquery,
GpuInSubqueryExec, GpuSubqueryBroadcastExec / GpuDynamicPruningExpression,
integration_tests dpp_test.py."""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


class TestScalarSubquery:
    def test_filter_by_scalar(self, sess, rng):
        t = pa.table({"k": np.arange(100, dtype=np.int64),
                      "v": rng.uniform(0, 100, 100)})
        df = sess.create_dataframe(t)
        avg = F.scalar_subquery(df.agg(F.avg(F.col("v")).alias("a")))
        got = df.filter(F.col("v") > avg).collect()
        pdf = t.to_pandas()
        want = pdf[pdf.v > pdf.v.mean()]
        assert len(got) == len(want)
        assert sorted(r[0] for r in got) == sorted(want.k.tolist())

    def test_scalar_in_projection(self, sess, rng):
        t = pa.table({"v": rng.uniform(0, 10, 50)})
        df = sess.create_dataframe(t)
        mx = F.scalar_subquery(df.agg(F.max(F.col("v")).alias("m")))
        got = df.select((F.col("v") / mx).alias("frac")).collect()
        pdf = t.to_pandas()
        want = (pdf.v / pdf.v.max()).tolist()
        assert np.allclose(sorted(r[0] for r in got), sorted(want))

    def test_nested_scalar(self, sess, rng):
        t = pa.table({"v": rng.uniform(0, 10, 64)})
        df = sess.create_dataframe(t)
        inner = F.scalar_subquery(df.agg(F.min(F.col("v")).alias("m")))
        mid = df.filter(F.col("v") > inner)
        outer = F.scalar_subquery(mid.agg(F.avg(F.col("v")).alias("a")))
        got = df.filter(F.col("v") > outer).count()
        pdf = t.to_pandas()
        thr = pdf.v[pdf.v > pdf.v.min()].mean()
        assert got == int((pdf.v > thr).sum())

    def test_empty_scalar_is_null(self, sess):
        t = pa.table({"v": pa.array([1.0, 2.0])})
        df = sess.create_dataframe(t)
        none_match = df.filter(F.col("v") > 100.0)
        mx = F.scalar_subquery(none_match.agg(F.max(F.col("v")).alias("m")))
        # NULL comparison -> no rows (SQL three-valued logic)
        assert df.filter(F.col("v") > mx).collect() == []

    def test_multi_row_scalar_raises(self, sess):
        t = pa.table({"v": pa.array([1.0, 2.0])})
        df = sess.create_dataframe(t)
        bad = F.scalar_subquery(df.select("v"))
        with pytest.raises(ValueError, match="scalar subquery"):
            df.filter(F.col("v") > bad).collect()


class TestInSubquery:
    def _tables(self, sess, rng, with_null=False):
        t = pa.table({"k": pa.array(rng.integers(0, 50, 300)),
                      "v": pa.array(rng.uniform(0, 1, 300))})
        sub_keys = [1, 5, 9, 13, 44] + ([None] if with_null else [])
        s = pa.table({"sk": pa.array(sub_keys, type=pa.int64())})
        return sess.create_dataframe(t), sess.create_dataframe(s), t

    def test_in_subquery_semi(self, sess, rng):
        df, sub, t = self._tables(sess, rng)
        got = df.filter(F.col("k").isin_subquery(sub.select("sk"))).collect()
        pdf = t.to_pandas()
        want = pdf[pdf.k.isin([1, 5, 9, 13, 44])]
        assert len(got) == len(want)

    def test_not_in_subquery_anti(self, sess, rng):
        df, sub, t = self._tables(sess, rng)
        got = df.filter(
            ~F.col("k").isin_subquery(sub.select("sk"))).collect()
        pdf = t.to_pandas()
        want = pdf[~pdf.k.isin([1, 5, 9, 13, 44])]
        assert len(got) == len(want)

    def test_not_in_with_null_subquery_is_empty(self, sess, rng):
        """SQL NOT IN over a subquery containing NULL matches nothing."""
        df, sub, t = self._tables(sess, rng, with_null=True)
        got = df.filter(
            ~F.col("k").isin_subquery(sub.select("sk"))).collect()
        assert got == []

    def test_in_subquery_with_extra_conjunct(self, sess, rng):
        df, sub, t = self._tables(sess, rng)
        got = df.filter(F.col("k").isin_subquery(sub.select("sk"))
                        & (F.col("v") > 0.5)).collect()
        pdf = t.to_pandas()
        want = pdf[pdf.k.isin([1, 5, 9, 13, 44]) & (pdf.v > 0.5)]
        assert len(got) == len(want)


class TestDPP:
    def _fact_dim(self, sess, tmp_path, rng, n_fact=50_000, n_dim=400):
        fact = pa.table({
            "f_key": pa.array(rng.integers(0, n_dim, n_fact)),
            "f_val": pa.array(rng.uniform(0, 100, n_fact)),
        })
        fpath = str(tmp_path / "fact.parquet")
        # many small row groups so range/in pruning has units to drop
        pq.write_table(fact, fpath, row_group_size=2000)
        dim = pa.table({
            "d_key": pa.array(np.arange(n_dim, dtype=np.int64)),
            "d_cat": pa.array((np.arange(n_dim) % 7).astype(np.int64)),
        })
        dpath = str(tmp_path / "dim.parquet")
        pq.write_table(dim, dpath)
        return (sess.read_parquet(fpath), sess.read_parquet(dpath),
                fact.to_pandas(), dim.to_pandas())

    def test_dpp_prunes_scan_rows(self, sess, tmp_path, rng):
        factdf, dimdf, fact, dim = self._fact_dim(sess, tmp_path, rng)
        # selective dim filter -> few keys -> IN-list runtime predicate
        q = (factdf.join(F.broadcast(dimdf.filter(F.col("d_cat") == 3)),
                         on=[("f_key", "d_key")])
             .agg(F.sum(F.col("f_val")).alias("s")))
        got = q.collect()[0][0]
        keys = set(dim.loc[dim.d_cat == 3, "d_key"])
        want = fact.loc[fact.f_key.isin(keys), "f_val"].sum()
        assert got == pytest.approx(want)

        # observability: with DPP off, the same query scans MORE rows
        from spark_rapids_tpu.plan.physical import CollectExec, ExecContext

        def scan_rows(dpp: bool):
            sess.conf.set("spark.rapids.tpu.sql.dpp.enabled", dpp)
            sess.conf.set("spark.rapids.tpu.sql.fileCache.enabled", False)
            try:
                phys = sess._plan_physical(q._plan)
                ctx = ExecContext(sess._tpu_conf(), device=sess.device)
                CollectExec(phys).collect_arrow(ctx)
                return sum(ms.values.get("numOutputRows", 0)
                           for op, ms in ctx.metrics.items()
                           if op.startswith("ScanExec"))
            finally:
                sess.conf.set("spark.rapids.tpu.sql.dpp.enabled", True)
                sess.conf.set("spark.rapids.tpu.sql.fileCache.enabled",
                              True)

        rows_with = scan_rows(True)
        rows_without = scan_rows(False)
        assert rows_with < rows_without

    def test_dpp_empty_build_short_circuits(self, sess, tmp_path, rng):
        factdf, dimdf, fact, dim = self._fact_dim(sess, tmp_path, rng)
        q = (factdf.join(F.broadcast(dimdf.filter(F.col("d_cat") == 99)),
                         on=[("f_key", "d_key")])
             .agg(F.count_star().alias("c")))
        assert q.collect()[0][0] == 0

    def test_dpp_date_keys(self, sess, tmp_path, rng):
        n = 20_000
        days = rng.integers(0, 1000, n)
        base = datetime.date(1995, 1, 1)
        fact = pa.table({
            "f_date": pa.array([base + datetime.timedelta(days=int(d))
                                for d in days], type=pa.date32()),
            "f_val": pa.array(rng.uniform(0, 10, n)),
        })
        fpath = str(tmp_path / "factd.parquet")
        pq.write_table(fact, fpath, row_group_size=2000)
        dim_days = [base + datetime.timedelta(days=int(d))
                    for d in range(100, 130)]
        dim = pa.table({"d_date": pa.array(dim_days, type=pa.date32())})
        dpath = str(tmp_path / "dimd.parquet")
        pq.write_table(dim, dpath)
        factdf = sess.read_parquet(fpath)
        dimdf = sess.read_parquet(dpath)
        q = (factdf.join(F.broadcast(dimdf), on=[("f_date", "d_date")])
             .agg(F.sum(F.col("f_val")).alias("s")))
        got = q.collect()[0][0]
        fpd = fact.to_pandas()
        want = fpd.loc[fpd.f_date.isin(dim_days), "f_val"].sum()
        assert got == pytest.approx(want)


class TestExistenceJoin:
    """ExistenceJoin (GpuHashJoin.scala ExistenceJoin handling): IN
    subqueries inside disjunctions rewrite to a boolean match column."""

    def test_in_subquery_inside_or(self, sess, rng):
        t = pa.table({"k": pa.array(rng.integers(0, 40, 300)),
                      "v": pa.array(rng.uniform(0, 1, 300))})
        sub = sess.create_dataframe(
            pa.table({"sk": pa.array([3, 7, 11], type=pa.int64())}))
        df = sess.create_dataframe(t)
        got = df.filter(F.col("k").isin_subquery(sub.select("sk"))
                        | (F.col("v") > 0.9)).collect()
        pdf = t.to_pandas()
        want = pdf[pdf.k.isin([3, 7, 11]) | (pdf.v > 0.9)]
        assert len(got) == len(want)
        assert all(len(r) == 2 for r in got)  # exists column dropped

    def test_two_in_subqueries_in_or(self, sess, rng):
        t = pa.table({"a": pa.array(rng.integers(0, 30, 200)),
                      "b": pa.array(rng.integers(0, 30, 200))})
        s1 = sess.create_dataframe(
            pa.table({"x": pa.array([1, 2], type=pa.int64())}))
        s2 = sess.create_dataframe(
            pa.table({"y": pa.array([25, 28], type=pa.int64())}))
        df = sess.create_dataframe(t)
        got = df.filter(F.col("a").isin_subquery(s1)
                        | F.col("b").isin_subquery(s2)).collect()
        pdf = t.to_pandas()
        want = pdf[pdf.a.isin([1, 2]) | pdf.b.isin([25, 28])]
        assert len(got) == len(want)

    def test_negated_in_disjunction_raises(self, sess, rng):
        t = pa.table({"k": pa.array(rng.integers(0, 10, 50))})
        sub = sess.create_dataframe(
            pa.table({"s": pa.array([1], type=pa.int64())}))
        df = sess.create_dataframe(t)
        with pytest.raises(NotImplementedError, match="negated IN"):
            df.filter((~F.col("k").isin_subquery(sub))
                      | (F.col("k") > 100)).collect()


class TestSmjRuntimeFilter:
    def test_shuffled_join_prunes_right_scan(self, sess, tmp_path, rng):
        """The materialized left side's key stats prune the right side's
        parquet scan (bloom-filter join runtime filter analog)."""
        sess.conf.set("spark.rapids.tpu.sql.autoBroadcastJoinThreshold",
                      -1)
        try:
            left = pa.table({
                "lk": pa.array(rng.integers(100, 120, 500)),
                "lv": pa.array(rng.uniform(0, 1, 500))})
            right = pa.table({
                "rk": pa.array(rng.integers(0, 1000, 40_000)),
                "rv": pa.array(rng.uniform(0, 1, 40_000))})
            rpath = str(tmp_path / "right.parquet")
            pq.write_table(right, rpath, row_group_size=2000)
            ldf = sess.create_dataframe(left)
            rdf = sess.read_parquet(rpath)
            q = (ldf.join(rdf, on=[("lk", "rk")])
                 .agg(F.sum(F.col("rv")).alias("s"),
                      F.count_star().alias("c")))
            got = q.collect()[0]
            lpd, rpd = left.to_pandas(), right.to_pandas()
            m = lpd.merge(rpd, left_on="lk", right_on="rk")
            assert got[1] == len(m)
            assert got[0] == pytest.approx(m.rv.sum())
        finally:
            sess.conf.set(
                "spark.rapids.tpu.sql.autoBroadcastJoinThreshold",
                10 * 1024 * 1024)

"""Differential acceptance for the full TPC-H suite module
(models/tpch_suite.py): all 22 queries, engine vs pandas oracle, through
the real parquet scan path at a tiny scale factor.  This is the same
(runner, oracle) registry bench.py times at SF1."""

import pytest

from spark_rapids_tpu.models import tpch_suite


@pytest.fixture(scope="module")
def db(session, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("tpch_sf_tiny"))
    dfs = tpch_suite.load_db(session, 0.002, out)
    pds = tpch_suite.load_pdb(0.002, out)
    return dfs, pds


@pytest.mark.parametrize("name", [f"q{i}" for i in range(1, 23)])
def test_suite_query_differential(db, name):
    dfs, pds = db
    runner, oracle = tpch_suite.QUERIES[name]
    got = runner(dfs)
    want = oracle(pds)
    err = tpch_suite.rows_rel_err(got, want)
    assert err < 1e-6, f"{name}: rel_err={err} ({len(got)} rows)"

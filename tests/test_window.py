"""Window function tests (window_function_test.py analog).

Differential: engine window results vs a transparent O(n^2) python oracle
that applies Spark frame semantics literally (peers, null skipping).
"""

import numpy as np
import pandas as pd
import pytest

from .support import DoubleGen, IntGen, assert_rows_equal, gen_table, pdf_rows


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def W():
    from spark_rapids_tpu.sql.window import Window
    return Window


@pytest.fixture(scope="module")
def wdf(session, rng):
    table, pdf = gen_table(rng, {
        "p": IntGen(lo=0, hi=5, nullable=False),
        "o": IntGen(lo=0, hi=20),
        "u": IntGen(lo=0, hi=10**6, nullable=False),  # unique-ish tiebreak
        "v": IntGen(lo=-50, hi=50),
        "d": DoubleGen(special=False, nullable=False),
    }, 240)
    # make u truly unique so ROWS frames are deterministic
    pdf = pdf.copy()
    pdf["u"] = np.arange(len(pdf), dtype=np.int64)
    import pyarrow as pa
    table = table.set_column(table.schema.get_field_index("u"), "u",
                             pa.array(pdf["u"].to_numpy()))
    return session.create_dataframe(table), pdf


# ------------------------------------------------------------------------------------
# Oracle
# ------------------------------------------------------------------------------------

def _null(x):
    return x is None or x is pd.NA or (isinstance(x, float) and np.isnan(x))


def oracle(pdf, parts, orders, func, frame=("rows", None, None), arg=None):
    """Window value per original row; Spark semantics, brute force."""
    rows = pdf_rows(pdf)
    cols = list(pdf.columns)

    def cell(r, c):
        return rows[r][cols.index(c)]

    n = len(rows)
    # partition groups
    groups = {}
    for i in range(n):
        key = tuple((cell(i, c) is None, cell(i, c)) for c in parts)
        groups.setdefault(key, []).append(i)
    out = [None] * n
    kind, lo, hi = frame
    for key, idxs in groups.items():
        # sort within partition by order cols asc nulls-first, stable
        def okey(i):
            return tuple((not _null(cell(i, c)),
                          cell(i, c) if not _null(cell(i, c)) else 0)
                         for c in orders)
        idxs = sorted(idxs, key=okey)
        m = len(idxs)
        okeys = [okey(i) for i in idxs]
        for pos, i in enumerate(idxs):
            if func == "row_number":
                out[i] = pos + 1
                continue
            if func == "rank":
                out[i] = okeys.index(okeys[pos]) + 1
                continue
            if func == "dense_rank":
                seen = []
                for k in okeys[: pos + 1]:
                    if not seen or seen[-1] != k:
                        seen.append(k)
                out[i] = len(seen)
                continue
            if func == "lag":
                src = pos - arg[0]
                out[i] = (cell(idxs[src], arg[1])
                          if 0 <= src < m else arg[2])
                continue
            if func == "lead":
                src = pos + arg[0]
                out[i] = (cell(idxs[src], arg[1])
                          if 0 <= src < m else arg[2])
                continue
            # framed aggregate over column arg
            if kind == "rows":
                a = 0 if lo is None else max(0, pos + lo)
                b = m - 1 if hi is None else min(m - 1, pos + hi)
            else:  # range
                if lo is None and hi is None:
                    a, b = 0, m - 1
                else:  # unbounded preceding .. current peer group end
                    a = 0
                    b = pos
                    while b + 1 < m and okeys[b + 1] == okeys[pos]:
                        b += 1
            if func == "count(*)":
                out[i] = max(0, b - a + 1)
                continue
            vals = [cell(idxs[j], arg) for j in range(a, b + 1)
                    if a <= b and not _null(cell(idxs[j], arg))]
            if func == "count":
                out[i] = len(vals)
            elif not vals:
                out[i] = None
            elif func == "sum":
                out[i] = sum(vals)
            elif func == "min":
                out[i] = min(vals)
            elif func == "max":
                out[i] = max(vals)
            elif func == "avg":
                out[i] = float(sum(vals)) / len(vals)
            else:
                raise ValueError(func)
    return out


def run_and_compare(df, pdf, wcol, parts, orders, func,
                    frame=("rows", None, None), arg=None, approx=False):
    got = df.select(*pdf.columns, wcol.alias("wout")).collect()
    exp_w = oracle(pdf, parts, orders, func, frame, arg)
    exp = [r + (exp_w[i],) for i, r in enumerate(pdf_rows(pdf))]
    assert_rows_equal(got, exp, approx_float=approx)


# ------------------------------------------------------------------------------------
# Ranking family
# ------------------------------------------------------------------------------------

def test_row_number(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u")
    run_and_compare(df, pdf, f.row_number().over(spec), ["p"], ["u"],
                    "row_number")


def test_rank_dense_rank_with_ties(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("o")  # o has ties and nulls
    run_and_compare(df, pdf, f.rank().over(spec), ["p"], ["o"], "rank")
    run_and_compare(df, pdf, f.dense_rank().over(spec), ["p"], ["o"],
                    "dense_rank")


def test_ntile_and_percent_rank(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u")
    got = df.select("p", "u",
                    f.ntile(4).over(spec).alias("nt"),
                    f.percent_rank().over(spec).alias("pr"),
                    f.cume_dist().over(spec).alias("cd")).to_pandas()
    exp = pdf[["p", "u"]].copy()
    g = pdf.sort_values(["p", "u"]).groupby("p")["u"]
    for p, grp in pdf.groupby("p"):
        sz = len(grp)
        order = grp.sort_values("u").index
        for pos, idx in enumerate(order):
            base, rem = sz // 4, sz % 4
            nt = (pos // (base + 1) if pos < (base + 1) * rem
                  else rem + (pos - (base + 1) * rem) // max(base, 1)) + 1
            exp.loc[idx, "nt"] = nt
            exp.loc[idx, "pr"] = pos / (sz - 1) if sz > 1 else 0.0
            exp.loc[idx, "cd"] = (pos + 1) / sz
    merged = got.merge(exp, on=["p", "u"], suffixes=("", "_e"))
    assert len(merged) == len(pdf)
    assert (merged["nt"] == merged["nt_e"]).all()
    assert np.allclose(merged["pr"], merged["pr_e"])
    assert np.allclose(merged["cd"], merged["cd_e"])


# ------------------------------------------------------------------------------------
# lag / lead
# ------------------------------------------------------------------------------------

def test_lag_lead(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u")
    run_and_compare(df, pdf, f.lag("v", 1).over(spec), ["p"], ["u"],
                    "lag", arg=(1, "v", None))
    run_and_compare(df, pdf, f.lead("v", 2).over(spec), ["p"], ["u"],
                    "lead", arg=(2, "v", None))


def test_lag_with_default(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u")
    run_and_compare(df, pdf, f.lag("u", 3, -1).over(spec), ["p"], ["u"],
                    "lag", arg=(3, "u", -1))


# ------------------------------------------------------------------------------------
# Framed aggregates
# ------------------------------------------------------------------------------------

def test_running_sum_rows(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u").rows_between(
        w.unboundedPreceding, w.currentRow)
    run_and_compare(df, pdf, f.sum(f.col("v")).over(spec), ["p"], ["u"],
                    "sum", ("rows", None, 0), "v")


def test_default_range_frame_ties(wdf):
    """ORDER BY with no explicit frame = RANGE UNBOUNDED..CURRENT (peers
    share the value)."""
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("o")
    run_and_compare(df, pdf, f.sum(f.col("v")).over(spec), ["p"], ["o"],
                    "sum", ("range", None, 0), "v")
    run_and_compare(df, pdf, f.count(f.col("v")).over(spec), ["p"], ["o"],
                    "count", ("range", None, 0), "v")


def test_whole_partition_agg(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p")
    run_and_compare(df, pdf, f.sum(f.col("v")).over(spec), ["p"], [],
                    "sum", ("rows", None, None), "v")
    run_and_compare(df, pdf, f.max(f.col("v")).over(spec), ["p"], [],
                    "max", ("rows", None, None), "v")
    run_and_compare(df, pdf, f.avg(f.col("d")).over(spec), ["p"], [],
                    "avg", ("rows", None, None), "d", approx=True)


def test_sliding_rows_frame(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u").rows_between(-2, 2)
    run_and_compare(df, pdf, f.sum(f.col("v")).over(spec), ["p"], ["u"],
                    "sum", ("rows", -2, 2), "v")
    run_and_compare(df, pdf, f.count(f.col("v")).over(spec), ["p"], ["u"],
                    "count", ("rows", -2, 2), "v")


def test_running_min_max(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u").rows_between(
        w.unboundedPreceding, 0)
    run_and_compare(df, pdf, f.min(f.col("v")).over(spec), ["p"], ["u"],
                    "min", ("rows", None, 0), "v")
    run_and_compare(df, pdf, f.max(f.col("v")).over(spec), ["p"], ["u"],
                    "max", ("rows", None, 0), "v")


def test_count_star_window(wdf):
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u").rows_between(
        w.unboundedPreceding, 0)
    run_and_compare(df, pdf, f.count_star().over(spec), ["p"], ["u"],
                    "count(*)", ("rows", None, 0), None)


def test_no_partition_window(wdf):
    """Empty PARTITION BY: one global partition."""
    df, pdf = wdf
    f, w = F(), W()
    spec = w.order_by("u")
    run_and_compare(df, pdf, f.row_number().over(spec), [], ["u"],
                    "row_number")


def test_multiple_windows_one_select(wdf):
    df, pdf = wdf
    f, w = F(), W()
    s1 = w.partition_by("p").order_by("u")
    got = df.select(
        "p", "u",
        f.row_number().over(s1).alias("rn"),
        f.sum(f.col("v")).over(s1.rows_between(w.unboundedPreceding, 0))
         .alias("rs"),
    ).collect()
    rn = oracle(pdf, ["p"], ["u"], "row_number")
    rs = oracle(pdf, ["p"], ["u"], "sum", ("rows", None, 0), "v")
    rows = pdf_rows(pdf[["p", "u"]])
    exp = [r + (rn[i], rs[i]) for i, r in enumerate(rows)]
    assert_rows_equal(got, exp)


def test_window_on_tpu_plan(wdf):
    """The window must actually plan on the device (no CPU fallback)."""
    df, _ = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u")
    s = df.select("p", f.row_number().over(spec).alias("rn")).explain_string()
    assert "Window" in s
    assert "!" not in s.split("Window")[1].split("\n")[0]


def test_sliding_min_max_cpu_fallback(wdf):
    """Bounded sliding min/max is declined by the device and must be
    computed correctly by the CPU fallback."""
    df, pdf = wdf
    f, w = F(), W()
    spec = w.partition_by("p").order_by("u").rows_between(-1, 0)
    run_and_compare(df, pdf, f.min(f.col("v")).over(spec), ["p"], ["u"],
                    "min", ("rows", -1, 0), "v")
    run_and_compare(df, pdf, f.max(f.col("v")).over(spec), ["p"], ["u"],
                    "max", ("rows", -1, 0), "v")


def test_frame_survives_order_by():
    """An explicit frame set before order_by must be preserved (PySpark
    WindowSpec semantics)."""
    w = W()
    spec = w.partition_by("p").rows_between(-1, 0).order_by("u")
    assert spec._spec.frame.kind == "rows"
    assert (spec._spec.frame.lo, spec._spec.frame.hi) == (-1, 0)
    # and the implicit default still recomputes
    spec2 = w.partition_by("p").order_by("u")
    assert spec2._spec.frame.kind == "range"


def test_window_string_partition_falls_back(session):
    """String partition keys → CPU fallback, same results."""
    import pyarrow as pa
    f, w = F(), W()
    table = pa.table({
        "s": pa.array(["a", "b", "a", "c", "b", "a", None, "c"]),
        "x": pa.array([1, 2, 3, 4, 5, 6, 7, 8], type=pa.int64()),
    })
    df = session.create_dataframe(table)
    spec = w.partition_by("s").order_by("x")
    out = df.select("s", "x", f.row_number().over(spec).alias("rn"))
    plan = out.explain_string()
    assert "!" in plan  # something fell back
    got = out.collect()
    pdf = table.to_pandas()
    exp_rn = pdf.sort_values(["x"]).groupby("s", dropna=False).cumcount() + 1
    svals = table.column("s").to_pylist()
    exp = [(svals[i], int(pdf["x"][i]), int(exp_rn[i]))
           for i in range(len(pdf))]
    assert_rows_equal(got, exp)


def test_lag_with_column_default(wdf):
    """Column-valued lag default must be permuted into sorted output order
    (regression: defaults were taken in input row order)."""
    df, pdf = wdf
    f, w = F(), W()
    from spark_rapids_tpu.sql.column import Column as C
    from spark_rapids_tpu import exprs as E
    from spark_rapids_tpu.windowfns import Lag, WindowExpression
    spec = w.partition_by("p").order_by("u")
    wexpr = C(WindowExpression(
        Lag(E.UnresolvedColumn("v"), 1, E.UnresolvedColumn("u")),
        spec._spec))
    got = df.select("p", "u", "v", wexpr.alias("wout")).collect()
    sp = pdf.sort_values(["p", "u"]).reset_index()
    exp_map = {}
    for p in sp["p"].unique():
        g = sp[sp["p"] == p]
        prev_v = None
        for _, row in g.iterrows():
            if prev_v is None:
                exp_map[(row["p"], row["u"])] = row["u"]  # default = u
            else:
                exp_map[(row["p"], row["u"])] = prev_v
            prev_v = row["v"] if not pd.isna(row["v"]) else np.nan
    for p_, u_, v_, wout in got:
        exp = exp_map[(p_, u_)]
        if isinstance(exp, float) and np.isnan(exp):
            assert wout is None
        else:
            assert wout == exp, (p_, u_, wout, exp)


def test_window_survives_injected_oom(session):
    """Window op under injectRetryOOM=1 retries and still yields correct
    results (GpuWindowExec withRetryNoSplit analog)."""
    import pyarrow as pa
    f, w = F(), W()
    table = pa.table({
        "p": pa.array([0, 0, 1, 1, 0, 1], type=pa.int64()),
        "x": pa.array([3, 1, 5, 2, 6, 4], type=pa.int64()),
    })
    df = session.create_dataframe(table)
    session.conf.set("spark.rapids.tpu.test.injectRetryOOM", 1)
    try:
        spec = w.partition_by("p").order_by("x")
        got = df.select("p", "x", f.row_number().over(spec).alias("rn")) \
                .collect()
    finally:
        session.conf.set("spark.rapids.tpu.test.injectRetryOOM", 0)
    exp = {(0, 1): 1, (0, 3): 2, (0, 6): 3, (1, 2): 1, (1, 4): 2, (1, 5): 3}
    assert len(got) == 6
    for p_, x_, rn in got:
        assert rn == exp[(p_, x_)]

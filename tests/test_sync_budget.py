"""Sync-budget regression tests (VERDICT r4 item 2).

Every blocking device→host fetch in the engine routes through
``utils.metrics.fetch`` (~0.1-0.2 s per round trip on the tunneled
chip), so the per-operator budgets below are the engine's latency
contract: a change that adds a fetch to the join/agg/collect hot path
fails here before it ships as a 2x suite regression.

Reference analog: the sync discipline that GpuExec operators get from
cuDF's stream-ordered batching (SURVEY.md §3.2); here the budget is
explicit because remote-TPU round trips are ~1000x costlier than a
local cudaMemcpy.
"""

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.utils.metrics import QueryStats, sync_budget


@pytest.fixture()
def sess():
    return srt.Session.get_or_create()


def _frame(sess, n, seed, **cols):
    rng = np.random.default_rng(seed)
    data = {}
    for name, spec in cols.items():
        kind, hi = spec
        if kind == "int":
            data[name] = rng.integers(0, hi, n).astype(np.int64)
        else:
            data[name] = rng.random(n)
    return sess.create_dataframe(data)


def test_scan_filter_agg_collect_budget(sess):
    """Q6-shape (scan→filter→scalar agg→collect): <= 2 blocking fetches."""
    df = _frame(sess, 4096, 1, a=("int", 100), b=("f", None))
    q = df.filter(srt.functions.col("a") < 50).agg(
        srt.functions.sum(srt.functions.col("b")).alias("s"))
    with sync_budget(2, "scan-filter-agg"):
        q.collect()


def test_join_agg_sort_budget(sess):
    """Q3-shape (join→grouped agg→sort→collect): the full pipeline must
    hold under 12 blocking fetches (measured 2026-07: 8-10 on this plan
    shape; the slack covers planner variation, not new per-batch syncs)."""
    f = srt.functions
    left = _frame(sess, 8192, 2, k=("int", 512), v=("f", None))
    right = _frame(sess, 512, 3, k2=("int", 512), w=("f", None))
    q = (left.join(right, on=[("k", "k2")])
         .group_by("k").agg(f.sum(f.col("v")).alias("sv"))
         .sort(f.col("sv").desc())
         .limit(10))
    with sync_budget(12, "join-agg-sort"):
        q.collect()


def test_counters_track_fetches(sess):
    """QueryStats counts fetches and bytes for a collect."""
    df = _frame(sess, 1024, 4, a=("int", 10))
    QueryStats.reset()
    df.collect()
    s = QueryStats.get()
    assert s.blocking_fetches >= 1
    assert s.fetch_bytes > 0

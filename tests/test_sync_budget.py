"""Sync-budget regression tests (VERDICT r4 item 2).

Every blocking device→host fetch in the engine routes through
``utils.metrics.fetch`` (~0.1-0.2 s per round trip on the tunneled
chip), so the per-operator budgets below are the engine's latency
contract: a change that adds a fetch to the join/agg/collect hot path
fails here before it ships as a 2x suite regression.

Async fetches (``utils.metrics.fetch_async``: the D2H copy rides behind
the dispatch front) are EXCLUDED from the blocking budget but still
traced and byte/wait-accounted through the same choke point — the
budget measures stalls, not transfers.

Reference analog: the sync discipline that GpuExec operators get from
cuDF's stream-ordered batching (SURVEY.md §3.2); here the budget is
explicit because remote-TPU round trips are ~1000x costlier than a
local cudaMemcpy.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils.metrics import QueryStats, sync_budget


@pytest.fixture()
def sess():
    return srt.Session.get_or_create()


def _frame(sess, n, seed, **cols):
    rng = np.random.default_rng(seed)
    data = {}
    for name, spec in cols.items():
        kind, hi = spec
        if kind == "int":
            data[name] = rng.integers(0, hi, n).astype(np.int64)
        else:
            data[name] = rng.random(n)
    return sess.create_dataframe(data)


def test_scan_filter_agg_collect_budget(sess):
    """Q6-shape (scan→filter→scalar agg→collect): <= 2 *blocking*
    fetches; the collect tail may additionally ride async."""
    df = _frame(sess, 4096, 1, a=("int", 100), b=("f", None))
    q = df.filter(srt.functions.col("a") < 50).agg(
        srt.functions.sum(srt.functions.col("b")).alias("s"))
    with sync_budget(2, "scan-filter-agg") as s:
        q.collect()
    assert s.blocking_fetches <= 2
    # every transfer — blocking or async — is still byte-accounted
    assert s.fetch_bytes > 0


def test_scan_agg_budget_holds_under_pipeline(sess):
    """The async pipeline must not ADD blocking fetches: the same plan
    holds the same budget at depth 0 (serial) and depth 2."""
    f = srt.functions
    df = _frame(sess, 4096, 7, a=("int", 100), b=("f", None))
    q = df.filter(f.col("a") < 50).agg(f.sum(f.col("b")).alias("s"))
    for depth in (0, 2):
        sess.conf.set("spark.rapids.tpu.sql.pipeline.depth", depth)
        try:
            with sync_budget(2, f"scan-filter-agg@depth{depth}"):
                q.collect()
        finally:
            sess.conf.unset("spark.rapids.tpu.sql.pipeline.depth")


def test_join_agg_sort_budget(sess):
    """Q3-shape (join→grouped agg→sort→collect): the full pipeline must
    hold under 12 blocking fetches (measured 2026-07: 8-10 on this plan
    shape; the slack covers planner variation, not new per-batch syncs)."""
    f = srt.functions
    left = _frame(sess, 8192, 2, k=("int", 512), v=("f", None))
    right = _frame(sess, 512, 3, k2=("int", 512), w=("f", None))
    q = (left.join(right, on=[("k", "k2")])
         .group_by("k").agg(f.sum(f.col("v")).alias("sv"))
         .sort(f.col("sv").desc())
         .limit(10))
    with sync_budget(12, "join-agg-sort"):
        q.collect()


def test_counters_track_fetches(sess):
    """QueryStats counts transfers and bytes for a collect — the tail
    fetch may be blocking (depth 0) or async (pipelined), but it is
    never unaccounted."""
    df = _frame(sess, 1024, 4, a=("int", 10))
    QueryStats.reset()
    df.collect()
    s = QueryStats.get()
    assert s.blocking_fetches + s.async_fetches >= 1
    assert s.fetch_bytes > 0


def test_async_fetch_excluded_from_budget_but_traced(monkeypatch):
    """fetch_async resolves outside the blocking budget yet through the
    same accounting: bytes, wait time, and SRT_SYNC_TRACE attribution."""
    monkeypatch.setattr(M, "_TRACE_SYNCS", True)
    M.SYNC_TRACE.clear()
    with sync_budget(0, "async-only"):  # zero BLOCKING fetches allowed
        fut = M.fetch_async(jnp.arange(1024, dtype=jnp.int64))
        vals = fut.result()
        assert vals.shape == (1024,)
        assert vals[-1] == 1023
    s = QueryStats.get()
    assert s.blocking_fetches == 0
    assert s.async_fetches == 1
    assert s.fetch_bytes >= 1024 * 8
    assert s.fetch_wait_s >= 0.0
    # traced with the async tag and the fetch_async call site
    assert len(M.SYNC_TRACE) == 1
    site, _dt = M.SYNC_TRACE[0]
    assert site.startswith("async|")
    assert "test_sync_budget" in site
    # resolving twice must not double-count
    fut.result()
    assert QueryStats.get().async_fetches == 1


def test_warm_cache_scan_agg_budget(sess, tmp_path):
    """Warm cross-query cache, Q6 shape (parquet scan→filter→scalar
    agg→collect): the hit path serves device-resident batches, so the
    ONLY blocking fetch is the collect tail — 0 before it."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.cache import clear_query_cache, get_query_cache
    f = srt.functions
    rng = np.random.default_rng(13)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "a": rng.integers(0, 100, 4096).astype(np.int64),
        "b": rng.random(4096)}), preserve_index=False), path)
    sess.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
    clear_query_cache()
    try:
        df = sess.read_parquet(path)
        q = df.filter(f.col("a") < 50).agg(f.sum(f.col("b")).alias("s"))
        warm = q.collect()  # populate pass
        with sync_budget(1, "warm-cache-scan-agg") as s:
            got = q.collect()
        assert got == warm
        assert s.blocking_fetches <= 1  # the collect tail, nothing else
        assert get_query_cache().hits >= 1
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.cache.enabled")
        clear_query_cache()


def _dense_join_query(sess, n=8192, seed=1):
    """Scan→filter→join→join→agg chain whose join build stats ride the
    dense path (unique arange build keys; denseMinProbeRows lowered by
    the caller) — the shape the region prologue batches."""
    f = srt.functions
    rng = np.random.default_rng(seed)
    fact = sess.create_dataframe({
        "k": rng.integers(0, 512, n).astype(np.int64),
        "j": rng.integers(0, 128, n).astype(np.int64),
        "v": rng.random(n)})
    d1 = sess.create_dataframe({"k": np.arange(512, dtype=np.int64),
                                "w": rng.random(512)})
    d2 = sess.create_dataframe({"j": np.arange(128, dtype=np.int64),
                                "u": rng.random(128)})
    return (fact.filter(f.col("k") < 400)
                .join(d1, "k", "inner").join(d2, "j", "inner")
                .group_by(f.col("k")).agg(f.sum(f.col("v")).alias("s")))


def _norm(rows):
    return sorted(tuple(r.values()) if isinstance(r, dict) else tuple(r)
                  for r in rows)


def _collect_with_stats(sess, q, **conf):
    for k, v in conf.items():
        sess.conf.set(k, v)
    st = QueryStats()
    tok = M._STATS_STACK.set(M._STATS_STACK.get() + (st,))
    try:
        return q.collect(), st
    finally:
        M._STATS_STACK.reset(tok)
        for k in conf:
            sess.conf.unset(k)


def test_fused_region_prologue_budget(sess):
    """The tentpole contract: a fused scan→filter→join→join→agg region
    batches its member stats syncs into the region prologue, so the
    two joins' build-stats fetches cost ONE prologue fetch — fusion-on
    pays strictly fewer blocking fetches than the per-operator path,
    and the fusion-off oracle stays exact."""
    from spark_rapids_tpu.memory.spill import get_catalog
    q = _dense_join_query(sess)
    sess.conf.set("spark.rapids.tpu.join.denseMinProbeRows", 1024)
    try:
        on, s_on = _collect_with_stats(
            sess, q, **{"spark.rapids.tpu.sql.fusion.enabled": True})
        off, s_off = _collect_with_stats(
            sess, q, **{"spark.rapids.tpu.sql.fusion.enabled": False})
    finally:
        sess.conf.unset("spark.rapids.tpu.join.denseMinProbeRows")
    assert s_on.fused_regions >= 1
    assert s_off.fused_regions == 0
    # both join-stat syncs collapsed into one batched prologue fetch:
    # at least one blocking round trip saved outright
    assert s_on.blocking_fetches <= s_off.blocking_fetches - 1
    # each region pays at most 2 batched resolves on this shape (the
    # join-stats prologue + the agg candidate-stats pull), never the
    # per-operator fetch count
    assert s_on.region_fetches <= 2 * s_on.fused_regions
    assert _norm(on) == _norm(off)
    get_catalog().assert_no_leaks()


def test_fusion_on_off_share_cache_entries(sess, tmp_path):
    """plan_fingerprint sees THROUGH FusedRegionExec: data cached by a
    fusion-on run must hit for the same query with fusion off (and vice
    versa) — the region is an execution grouping, not a different query."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.cache import clear_query_cache, get_query_cache
    f = srt.functions
    rng = np.random.default_rng(23)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.Table.from_pandas(pd.DataFrame({
        "a": rng.integers(0, 100, 4096).astype(np.int64),
        "b": rng.random(4096)}), preserve_index=False), path)
    sess.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
    clear_query_cache()
    try:
        df = sess.read_parquet(path)
        q = df.filter(f.col("a") < 50).agg(f.sum(f.col("b")).alias("s"))
        on, _ = _collect_with_stats(
            sess, q, **{"spark.rapids.tpu.sql.fusion.enabled": True})
        hits0 = get_query_cache().hits
        off, _ = _collect_with_stats(
            sess, q, **{"spark.rapids.tpu.sql.fusion.enabled": False})
        assert get_query_cache().hits > hits0
        assert _norm(on) == _norm(off)
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.cache.enabled")
        clear_query_cache()


def test_fusion_concurrent_queries_stay_scoped(sess):
    """Two queries running fused regions concurrently (the scheduler
    path): the contextvar-carried region scope must not leak across
    threads — each query batches only its own stats, results exact."""
    import threading

    from spark_rapids_tpu.memory.spill import get_catalog
    qs = [_dense_join_query(sess, seed=s) for s in (11, 12)]
    oracle = []
    for q in qs:
        out, _ = _collect_with_stats(
            sess, q, **{"spark.rapids.tpu.sql.fusion.enabled": False})
        oracle.append(_norm(out))
    sess.conf.set("spark.rapids.tpu.sql.fusion.enabled", True)
    results = [None, None]
    errors = []

    def run(i):
        try:
            results[i] = _norm(qs[i].collect())
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    try:
        ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.fusion.enabled")
    assert not errors
    assert results[0] == oracle[0]
    assert results[1] == oracle[1]
    get_catalog().assert_no_leaks()


def test_deferred_metrics_do_not_block(sess):
    """Deferred operator metrics resolve via the async path: reading
    them after a query adds no blocking fetch."""
    from spark_rapids_tpu.utils.metrics import MetricSet
    QueryStats.reset()
    m = MetricSet("op@test")
    m.add_deferred("numOutputRows", jnp.sum(jnp.arange(10)))
    before = QueryStats.get().blocking_fetches
    assert m["numOutputRows"] == 45
    assert QueryStats.get().blocking_fetches == before
    assert QueryStats.get().async_fetches >= 1

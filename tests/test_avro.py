"""Avro container format: pure-python reader/writer round trips
(GpuAvroScan / AvroDataFileReader analog)."""

import datetime
import os
import zlib

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.io.avro import (read_avro, read_avro_records,
                                      write_avro)


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_roundtrip_primitives(tmp_path):
    t = pa.table({
        "i": pa.array([1, None, 3], type=pa.int64()),
        "d": pa.array([1.5, 2.5, None]),
        "b": pa.array([True, False, None]),
        "s": pa.array(["x", None, "zzz"]),
    })
    p = str(tmp_path / "a.avro")
    write_avro(t, p)
    back = read_avro(p)
    assert back.to_pydict() == t.to_pydict()


def test_roundtrip_date_timestamp(tmp_path):
    t = pa.table({
        "dt": pa.array([datetime.date(1994, 1, 1), None], type=pa.date32()),
        "ts": pa.array([datetime.datetime(2001, 2, 3, 4, 5, 6, 789000),
                        None], type=pa.timestamp("us")),
    })
    p = str(tmp_path / "a.avro")
    write_avro(t, p)
    back = read_avro(p)
    assert back.column("dt").to_pylist() == t.column("dt").to_pylist()
    assert back.column("ts").to_pylist() == t.column("ts").to_pylist()


def test_null_codec_and_nested_record_read(tmp_path):
    """Hand-built avro file with codec null + nested record (the shape
    Iceberg manifests use)."""
    from spark_rapids_tpu.io.avro import _Writer, _MAGIC
    import json
    schema = {
        "type": "record", "name": "entry",
        "fields": [
            {"name": "status", "type": "int"},
            {"name": "data_file", "type": {
                "type": "record", "name": "df",
                "fields": [
                    {"name": "path", "type": "string"},
                    {"name": "count", "type": "long"},
                    {"name": "tags", "type": {"type": "array",
                                              "items": "string"}},
                ]}},
        ]}
    w = _Writer()
    w.write(_MAGIC)
    w.long(1)
    w.string("avro.schema")
    w.bytes_(json.dumps(schema).encode())
    w.long(0)
    sync = b"S" * 16
    w.write(sync)
    body = _Writer()
    for i in range(3):
        body.long(i)            # status
        body.string(f"f{i}.parquet")
        body.long(i * 100)
        body.long(2)            # array block of 2
        body.string("a")
        body.string("b")
        body.long(0)            # array end
    payload = body.getvalue()
    w.long(3)
    w.long(len(payload))
    w.write(payload)
    w.write(sync)
    p = str(tmp_path / "m.avro")
    with open(p, "wb") as f:
        f.write(w.getvalue())

    schema_back, rows = read_avro_records(p)
    assert len(rows) == 3
    assert rows[1] == {"status": 1,
                       "data_file": {"path": "f1.parquet", "count": 100,
                                     "tags": ["a", "b"]}}


def test_session_read_write_avro(session, tmp_path):
    f = F()
    t = pa.table({"k": pa.array([1, 2, 1], type=pa.int64()),
                  "v": pa.array([10.0, 20.0, 30.0])})
    out = str(tmp_path / "out")
    session.create_dataframe(t).write.avro(out)
    files = [n for n in os.listdir(out) if n.endswith(".avro")]
    assert len(files) == 1
    back = session.read_avro(out)
    got = back.group_by("k").agg(f.sum(f.col("v")).alias("s")).collect()
    assert sorted(got) == [(1, 40.0), (2, 20.0)]


def test_hive_text(session, tmp_path):
    from spark_rapids_tpu.batch import Field, Schema
    from spark_rapids_tpu import types as T
    d = str(tmp_path / "h")
    os.makedirs(d)
    with open(os.path.join(d, "000000_0"), "w") as fh:
        fh.write("1\x01a\n2\x01b\n")
    sch = Schema([Field("id", T.INT64, True), Field("name", T.STRING, True)])
    got = session.read_hive_text(d, schema=sch).collect()
    assert got == [(1, "a"), (2, "b")]

"""String keys on device via dictionary codes (ops/strings.py).

The reference handles strings natively in cudf; the TPU redesign encodes
string group/join keys to int32 dictionary codes, operates on codes, and
decodes at the output boundary.  These tests pin: correctness vs a pandas
oracle, null-key semantics (group: nulls group together; join: nulls never
match), multi-batch dictionary consistency, and that the plans stay ON
device (validateExecsOnTpu would flag a silent fallback).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from .support import IntGen, StringGen, assert_rows_equal, gen_table


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def _no_fallback(df):
    plan = df.explain_string()
    body = plan.splitlines()[2:]
    assert not any(ln.strip().startswith("!") for ln in body), plan


@pytest.fixture(scope="module")
def kdf(session, rng):
    table, pdf = gen_table(rng, {
        "k": StringGen(alphabet="abcde", max_len=2, nullable=True),
        "v": IntGen(lo=-100, hi=100, dtype="int64", nullable=False),
    }, 500)
    return session.create_dataframe(table), pdf


class TestStringGroupBy:
    def test_grouped_sum_count(self, kdf):
        df, pdf = kdf
        f = F()
        out = df.group_by("k").agg(f.sum(f.col("v")).alias("s"),
                                   f.count_star().alias("c"))
        _no_fallback(out)
        got = out.collect()
        g = pdf.groupby("k", dropna=False)["v"]
        exp = [(None if k is pd.NA or (isinstance(k, float) and np.isnan(k))
                else k, int(s), int(c))
               for (k, s), (_, c) in zip(g.sum().items(), g.count().items())]
        # pandas count() skips NA values of v (none here) — count_star counts rows
        sizes = pdf.groupby("k", dropna=False).size()
        exp = [(None if (k is pd.NA or (isinstance(k, float) and np.isnan(k)))
                else k, int(g.sum()[k]), int(sizes[k])) for k in sizes.index]
        assert_rows_equal(got, exp)

    def test_distinct_strings(self, kdf):
        df, pdf = kdf
        out = df.select("k").distinct()
        _no_fallback(out)
        got = sorted([r[0] for r in out.collect()],
                     key=lambda x: (x is None, x))
        uniq = set()
        for k in pdf["k"]:
            uniq.add(None if k is pd.NA else k)
        exp = sorted(uniq, key=lambda x: (x is None, x))
        assert got == exp

    def test_multi_key_string_plus_int(self, session, rng):
        f = F()
        table, pdf = gen_table(rng, {
            "k": StringGen(alphabet="xy", max_len=1, nullable=True),
            "g": IntGen(lo=0, hi=3, dtype="int32", nullable=False),
            "v": IntGen(lo=0, hi=10, dtype="int64", nullable=False),
        }, 200)
        df = session.create_dataframe(table)
        out = df.group_by("k", "g").agg(f.sum(f.col("v")).alias("s"))
        _no_fallback(out)
        got = out.collect()
        sizes = pdf.groupby(["k", "g"], dropna=False)["v"].sum()
        exp = [((None if k is pd.NA else k), int(g_), int(s))
               for (k, g_), s in sizes.items()]
        assert_rows_equal(got, exp)

    def test_multibatch_dictionary_consistency(self, session):
        """Keys spread across many scan batches must still merge: the
        dictionary is incremental across batches."""
        f = F()
        n = 5000
        keys = [f"k{i % 7}" for i in range(n)]
        vals = list(range(n))
        df = session.create_dataframe(pa.table({
            "k": keys, "v": pa.array(vals, type=pa.int64())}))
        out = df.group_by("k").agg(f.sum(f.col("v")).alias("s"))
        got = dict(out.collect())
        pdf = pd.DataFrame({"k": keys, "v": vals})
        exp = pdf.groupby("k")["v"].sum().to_dict()
        assert got == exp


@pytest.fixture(scope="module")
def join_dfs(session, rng):
    lt, lp = gen_table(rng, {
        "k": StringGen(alphabet="abcdef", max_len=2, nullable=True),
        "x": IntGen(lo=0, hi=1000, dtype="int64", nullable=False),
    }, 300)
    rt, rp = gen_table(rng, {
        "k": StringGen(alphabet="cdefgh", max_len=2, nullable=True),
        "y": IntGen(lo=0, hi=1000, dtype="int64", nullable=False),
    }, 200)
    return (session.create_dataframe(lt), lp,
            session.create_dataframe(rt), rp)


def _pd_join(lp, rp, how):
    l = lp.copy()
    r = rp.copy()
    l["k"] = l["k"].astype(object).where(l["k"].notna(), None)
    r["k"] = r["k"].astype(object).where(r["k"].notna(), None)
    l["_lk"] = l["k"]
    r["_rk"] = r["k"]
    if how in ("semi", "anti"):
        keys = set(r["k"].dropna())
        m = l["k"].apply(lambda v: v is not None and v in keys)
        out = l[m] if how == "semi" else l[~m]
        return [(None if k is None else k, int(x))
                for k, x in zip(out["k"], out["x"])]
    mhow = {"inner": "inner", "left": "left", "right": "right",
            "full": "outer"}[how]
    # drop null keys from the MATCHING but keep rows (SQL semantics)
    merged = l.dropna(subset=["k"]).merge(r.dropna(subset=["k"]), on="k",
                                          how="inner")
    rows = [(k, int(x), int(y))
            for k, x, y in zip(merged["k"], merged["x"], merged["y"])]
    if how in ("left", "full"):
        matched = set(merged["_lk"].dropna())
        for k, x in zip(l["k"], l["x"]):
            if k is None or k not in matched:
                rows.append((k, int(x), None))
    if how in ("right", "full"):
        matched = set(merged["_rk"].dropna())
        for k, y in zip(r["k"], r["y"]):
            if k is None or k not in matched:
                rows.append((k, None, int(y)))
    return rows


class TestStringJoins:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                     "semi", "anti"])
    def test_vs_pandas(self, join_dfs, how):
        ldf, lp, rdf, rp = join_dfs
        out = ldf.join(rdf, on="k", how=how)
        _no_fallback(out)
        got = out.collect()
        exp = _pd_join(lp, rp, how)
        assert_rows_equal(got, exp)

    def test_join_then_group(self, join_dfs):
        """Exchange → join → aggregate chain with string keys stays on
        device end to end."""
        f = F()
        ldf, lp, rdf, rp = join_dfs
        out = (ldf.join(rdf, on="k", how="inner")
               .group_by("k").agg(f.count_star().alias("c")))
        _no_fallback(out)
        got = dict(out.collect())
        exp_rows = _pd_join(lp, rp, "inner")
        exp = {}
        for k, _x, _y in exp_rows:
            exp[k] = exp.get(k, 0) + 1
        assert got == exp

"""Bitwise + hash expressions (bitwise.scala / hashFunctions analogs).

Oracles: Spark golden murmur3 values (hash(1) = -559580957 for IntegerType,
seed 42), the independent python-xxhash library, and the native C kernels.
"""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


def _col(session, name, rows, dtype=None):
    arr = pa.array(rows, type=dtype)
    return session.create_dataframe(pa.table({name: arr}))


class TestBitwise:
    def test_and_or_xor(self, session):
        df = session.create_dataframe({
            "a": np.array([0b1100, -1, 0], np.int64),
            "b": np.array([0b1010, 7, 5], np.int64)})
        rows = df.select(
            F.col("a").bitwiseAND(F.col("b")).alias("and_"),
            F.col("a").bitwiseOR(F.col("b")).alias("or_"),
            F.col("a").bitwiseXOR(F.col("b")).alias("xor_")).collect()
        assert rows == [(0b1000, 0b1110, 0b0110), (7, -1, -8), (0, 5, 5)]

    def test_not_and_nulls(self, session):
        df = _col(session, "a", [5, None, -1], pa.int32())
        rows = df.select(F.bitwise_not(F.col("a")).alias("n")).collect()
        assert rows == [(-6,), (None,), (0,)]

    def test_mixed_width_promotes(self, session):
        df = session.create_dataframe(pa.table({
            "i": pa.array([3], pa.int32()), "l": pa.array([5], pa.int64())}))
        rows = df.select(F.col("i").bitwiseAND(F.col("l")).alias("x"))
        assert rows.collect() == [(1,)]

    def test_bitwise_on_double_is_analysis_error(self, session):
        """Spark rejects bitwise over non-integral operands at analysis;
        silently truncating 1.5 would corrupt results."""
        df = session.create_dataframe({"x": [1.5]})
        q = df.select(F.col("x").bitwiseAND(F.lit(1)).alias("b"))
        with pytest.raises(TypeError, match="integral"):
            q.collect()

    def test_shift_on_double_is_analysis_error(self, session):
        df = session.create_dataframe({"x": [2.9]})
        q = df.select(F.shiftleft(F.col("x"), F.lit(1)).alias("s"))
        with pytest.raises(TypeError, match="integral"):
            q.collect()


class TestShifts:
    def test_jvm_count_masking(self, session):
        df = _col(session, "a", [8, -8], pa.int32())
        rows = df.select(
            F.shiftleft(F.col("a"), F.lit(33)).alias("l"),  # == << 1
            F.shiftright(F.col("a"), F.lit(1)).alias("r"),
            F.shiftrightunsigned(F.col("a"), F.lit(1)).alias("u")).collect()
        assert rows[0] == (16, 4, 4)
        assert rows[1] == (-16, -4, 2147483644)  # JVM -8 >>> 1

    def test_long_shifts(self, session):
        df = _col(session, "a", [1, -2], pa.int64())
        rows = df.select(
            F.shiftleft(F.col("a"), F.lit(40)).alias("l"),
            F.shiftrightunsigned(F.col("a"), F.lit(40)).alias("u")).collect()
        assert rows[0] == (1 << 40, 0)
        assert rows[1] == (-(2 << 40) % (1 << 64) - (1 << 64),
                           (2**64 - 2) >> 40)

    def test_shift_small_int_widens_to_int(self, session):
        df = _col(session, "a", [4], pa.int16())
        assert df.select(
            F.shiftleft(F.col("a"), F.lit(2)).alias("s")).collect() \
            == [(16,)]


class TestMurmur3Hash:
    def test_spark_golden_values(self, session):
        df = _col(session, "a", [1, 0, 42], pa.int32())
        rows = df.select(F.hash(F.col("a")).alias("h")).collect()
        assert [r[0] for r in rows] == [-559580957, 933211791, 29417773]

    def test_device_matches_native_host_fold(self, session):
        from spark_rapids_tpu import native
        vals = np.array([0, 1, -5, 2**40, -2**50], np.int64)
        df = _col(session, "a", vals.tolist(), pa.int64())
        got = [r[0] for r in df.select(F.hash(F.col("a")).alias("h"))
               .collect()]
        expect = native.murmur3_long(vals, 42).tolist()
        assert got == expect

    def test_multi_column_fold_with_nulls(self, session):
        from spark_rapids_tpu import native
        df = session.create_dataframe(pa.table({
            "i": pa.array([1, None, 3], pa.int32()),
            "l": pa.array([10, 20, None], pa.int64())}))
        got = [r[0] for r in
               df.select(F.hash(F.col("i"), F.col("l")).alias("h"))
               .collect()]
        # independent host fold: int column then long column, null = pass
        h = np.full(3, 42, np.int32)
        new = native.murmur3_int(np.array([1, 0, 3], np.int32), h)
        h = np.where([True, False, True], new, h)
        new = native.murmur3_long(np.array([10, 20, 0], np.int64), h)
        h = np.where([True, True, False], new, h)
        assert got == h.tolist()
        assert all(g is not None for g in got)  # hash is never null

    def test_double_normalization(self, session):
        df = _col(session, "a", [0.0, -0.0], pa.float64())
        rows = [r[0] for r in df.select(F.hash(F.col("a")).alias("h"))
                .collect()]
        assert rows[0] == rows[1]  # -0.0 hashes like +0.0


class TestXxHash64:
    # Golden values from the python-xxhash library, precomputed once:
    # `xxh64(int64(v).tobytes(), seed=42)` (8-byte path) and
    # `xxh64(int32(v).tobytes(), seed=42)` (4-byte path), two's-complement.
    GOLDEN_LONG = {0: -5252525462095825812, 1: -7001672635703045582,
                   -7: -1663473129717591079, 2**40: 1821704621099523357}
    GOLDEN_INT = {1: -6698625589789238999, -2: 6162728026222640212,
                  1000: -3226198733444762270}

    def test_against_xxhash_library_goldens(self, session):
        vals = list(self.GOLDEN_LONG)
        df = _col(session, "a", vals, pa.int64())
        got = [r[0] for r in df.select(F.xxhash64(F.col("a")).alias("h"))
               .collect()]
        assert got == [self.GOLDEN_LONG[v] for v in vals]

    def test_int_width_path(self, session):
        vals = list(self.GOLDEN_INT)
        df = _col(session, "a", vals, pa.int32())
        got = [r[0] for r in df.select(F.xxhash64(F.col("a")).alias("h"))
               .collect()]
        assert got == [self.GOLDEN_INT[v] for v in vals]

    # xxh64(s.encode(), seed=42), precomputed with python-xxhash,
    # two's-complement int64
    GOLDEN_STR = {"": -7444071767201028348,
                  "abc": 1423657621850124518,
                  "héllo": 501425390238239234,
                  "a longer string to cross eight bytes":
                      8989899728738319250}

    def test_string_hashing_on_cpu_path(self, session):
        vals = list(self.GOLDEN_STR) + [None]
        df = _col(session, "s", vals, pa.string())
        q = df.select(F.xxhash64(F.col("s")).alias("h"))
        assert "!" in q.explain_string()  # strings -> CPU fallback
        got = [r[0] for r in q.collect()]
        assert got[:-1] == [self.GOLDEN_STR[v] for v in vals[:-1]]
        # null folds the seed through: xxh64 result of just the seed state
        assert got[-1] is not None

    def test_string_murmur3_matches_native_kernel(self, session):
        from spark_rapids_tpu import native
        vals = ["", "spark", "héllo wörld", None, "tail7b"]
        df = _col(session, "s", vals, pa.string())
        got = [r[0] for r in df.select(F.hash(F.col("s")).alias("h"))
               .collect()]
        enc = [(v or "").encode() for v in vals]
        offsets = np.zeros(len(enc) + 1, dtype=np.int64)
        for i, b in enumerate(enc):
            offsets[i + 1] = offsets[i] + len(b)
        expect = native.murmur3_utf8(
            np.frombuffer(b"".join(enc), np.uint8), offsets, 42)
        h = np.where([v is not None for v in vals], expect, 42)
        assert got == h.tolist()

    def test_cpu_host_twin_matches_device(self, session):
        """eval_host (numpy) and eval (jax) must agree bit-for-bit."""
        from spark_rapids_tpu import bitwisefns as B
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.exprs import BoundReference
        vals = np.array([3, -9, 2**33], np.int64)
        e = B.XxHash64(BoundReference(0, T.INT64, False, "a"))
        host, _ = e.eval_host(lambda c: (vals, None), 3)
        df = _col(session, "a", vals.tolist(), pa.int64())
        dev = [r[0] for r in df.select(F.xxhash64(F.col("a")).alias("h"))
               .collect()]
        assert host.tolist() == dev

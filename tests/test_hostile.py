"""Hostile-input survival (ISSUE 20): front-door armor + the fuzzer.

Covers the acceptance surface: the three named attacks each rejected
typed in bounded time and memory (the 2 GB lying length prefix, the
slowloris handshake, the expression depth bomb), conf-bounded frame
and spec limits, the per-connection decode-error strike budget and its
penalty box, leak-free teardown after every attack class, the
checked-in fuzz corpus replaying clean at tier-1, and the satellite
wiring (ops read caps, the ``server.malformed`` injector point, the
``fuzz_survival`` perfwatch record, docs).
"""

import json
import os
import socket
import time

import pytest

from spark_rapids_tpu.config import ALL_ENTRIES
from spark_rapids_tpu.memory.spill import get_catalog
from spark_rapids_tpu.server import SqlFrontDoor, WireClient, WireError
from spark_rapids_tpu.server import protocol as P
from spark_rapids_tpu.server.spec import BadSpec, SpecLimits, validate_spec
from tools import fuzzwire as FW
from tools import loadgen as LG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "fuzz_corpus")

# tight hostile-input windows so every reap lands in test time; the
# penalty box stays SHORT because every test shares 127.0.0.1
HOSTILE_SETTINGS = {
    "spark.rapids.tpu.server.handshakeTimeoutMs": 800.0,
    "spark.rapids.tpu.server.frameTimeoutMs": 800.0,
    "spark.rapids.tpu.server.maxControlFrameBytes": 64 << 10,
    "spark.rapids.tpu.server.maxDecodeErrors": 3,
    "spark.rapids.tpu.server.penaltyBoxMs": 300.0,
    "spark.rapids.tpu.server.ops.maxRequestBytes": 1024,
    "spark.rapids.tpu.server.ops.requestTimeoutMs": 800.0,
}


@pytest.fixture(scope="module")
def hostile(session):
    """One armored door over the loadgen tables (the corpus spec cases
    speak the loadgen template schema)."""
    s = session
    orders, customers = LG.build_tables(4000, 20260807)
    s.conf.set("spark.rapids.tpu.sql.batchSizeRows", 2000)
    door = SqlFrontDoor(s, settings=dict(HOSTILE_SETTINGS)).start()
    tables = {"orders": lambda: s.create_dataframe(orders),
              "customers": lambda: s.create_dataframe(customers)}
    for name, f in tables.items():
        door.register_table(name, f)
    oracle = LG.Oracle(s, tables)
    yield s, door, oracle
    door.close()
    s.conf.unset("spark.rapids.tpu.sql.batchSizeRows")


AGG = LG.templates()["seg_rollup"][0]


def _await_clean(s, door, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if s.scheduler().running() == 0 \
                and door.snapshot()["queries_inflight"] == 0:
            return True
        time.sleep(0.05)
    return False


def _sit_out_penalty_box():
    time.sleep(HOSTILE_SETTINGS[
        "spark.rapids.tpu.server.penaltyBoxMs"] / 1000.0 + 0.1)


def _door_still_serves(door, oracle):
    with WireClient("127.0.0.1", door.port, tenant="after") as c:
        spec, pools = LG.templates()["seg_rollup"]
        r = c.query(spec, params=list(pools[0]))
        assert r.stats["status"] == "done"
        assert LG._norm_rows(r.rows()) == oracle.expected(
            "seg_rollup", spec, list(pools[0]))


def _authed(door, timeout=6.0):
    sock = FW._dial("127.0.0.1", door.port, timeout)
    sock.sendall(FW._frame_bytes(*FW._base_frame("hello")))
    P.recv_frame(sock, expect=(P.RSP_WELCOME,))
    return sock


# ---------------------------------------------------------------------------------
# The named attacks
# ---------------------------------------------------------------------------------

class TestFrameArmor:
    def test_2g_lying_length_typed_without_allocation(self, hostile):
        """THE header attack: a length prefix claiming 2 GB must be
        answered typed BEFORE any allocation — bounded time is the
        observable (an allocate-then-read door would stall for the
        frame deadline or OOM, not answer in milliseconds)."""
        s, door, oracle = hostile
        before = door.snapshot()
        sock = FW._dial("127.0.0.1", door.port, 6.0)
        try:
            t0 = time.monotonic()
            sock.sendall(P.FRAME.pack(P.REQ_SUBMIT, 2 << 30, 0))
            with pytest.raises(WireError) as ei:
                while True:
                    P.recv_frame(sock)
            elapsed = time.monotonic() - t0
        finally:
            sock.close()
        assert ei.value.code == "BAD_REQUEST"
        assert "maxControlFrameBytes" in str(ei.value)
        assert elapsed < 0.5, f"oversize answer took {elapsed:.2f}s"
        after = door.snapshot()
        assert after["decode_errors"] > before["decode_errors"]
        assert after["hostile_disconnects"] > before["hostile_disconnects"]
        _door_still_serves(door, oracle)

    def test_batch_type_cannot_shop_for_the_big_cap(self, hostile):
        """Inbound frames ALL get the control cap — claiming to be a
        BATCH does not unlock ``maxFrameBytes``."""
        s, door, oracle = hostile
        sock = FW._dial("127.0.0.1", door.port, 6.0)
        try:
            sock.sendall(P.FRAME.pack(P.RSP_BATCH, 100 << 20, 0))
            with pytest.raises(WireError) as ei:
                while True:
                    P.recv_frame(sock)
        finally:
            sock.close()
        assert ei.value.code == "BAD_REQUEST"

    def test_resumable_strike_keeps_the_connection(self, hostile):
        """A malformed frame with its payload on the wire costs a
        strike, answered typed — and the SAME connection then serves a
        well-formed request (the stream was consumed to a boundary)."""
        s, door, oracle = hostile
        sock = _authed(door)
        try:
            payload = b"junk"
            from spark_rapids_tpu.faults import integrity
            sock.sendall(P.FRAME.pack(b"Z", len(payload),
                                      integrity.checksum(payload))
                         + payload)
            with pytest.raises(WireError) as ei:
                P.recv_frame(sock)
            assert ei.value.code == "BAD_REQUEST"
            assert ei.value.reason == "malformed"
            assert "strike 1/3" in (ei.value.detail or "")
            sock.sendall(FW._frame_bytes(P.REQ_STATUS, b""))
            ftype, _ = P.recv_frame(sock, expect=(P.RSP_STATUS,))
            assert ftype == P.RSP_STATUS
        finally:
            sock.close()

    def test_strike_budget_burn_disconnects_and_boxes(self, hostile):
        s, door, oracle = hostile
        before = door.snapshot()
        sock = _authed(door)
        codes = []
        try:
            from spark_rapids_tpu.faults import integrity
            bad = P.FRAME.pack(b"Z", 1, integrity.checksum(b"x")) + b"x"
            for _ in range(3):
                sock.sendall(bad)
                with pytest.raises(WireError) as ei:
                    P.recv_frame(sock)
                codes.append(ei.value.code)
            # the budget is burned: the door hung up after the third
            with pytest.raises((ConnectionError, OSError, WireError)):
                sock.sendall(bad)
                P.recv_frame(sock)
        finally:
            sock.close()
        assert codes == ["BAD_REQUEST"] * 3
        # the immediate re-dial meets the penalty box, typed + hinted
        s2 = FW._dial("127.0.0.1", door.port, 6.0)
        try:
            with pytest.raises(WireError) as ei:
                P.recv_frame(s2)
            assert ei.value.code == "REJECTED"
            assert ei.value.reason == "penalty_box"
            assert ei.value.retry_after_ms > 0
        finally:
            s2.close()
        after = door.snapshot()
        assert after["hostile_disconnects"] > before["hostile_disconnects"]
        assert after["penalty_refusals"] > before["penalty_refusals"]
        # the box EXPIRES: this is a brake, not a ban
        _sit_out_penalty_box()
        _door_still_serves(door, oracle)

    def test_preauth_garbage_is_one_typed_disconnect(self, hostile):
        """Strangers get no strike budget: garbage before HELLO is one
        typed answer and a closed socket."""
        s, door, oracle = hostile
        sock = FW._dial("127.0.0.1", door.port, 6.0)
        try:
            sock.sendall(b"\xde\xad\xbe\xef" * 8)
            out = FW._read_outcome(sock, 6.0)
        finally:
            sock.close()
        assert out.startswith("typed:")


class TestSlowloris:
    def test_silent_handshake_reaped_at_deadline(self, hostile):
        """Dial and say nothing: the handshake deadline reaps the
        connection, typed, near ``handshakeTimeoutMs`` — not at the
        (much longer) idle timeout, not never."""
        s, door, oracle = hostile
        sock = FW._dial("127.0.0.1", door.port, 10.0)
        t0 = time.monotonic()
        try:
            out = FW._read_outcome(sock, 6.0)
            elapsed = time.monotonic() - t0
        finally:
            sock.close()
        assert out == "typed:BAD_REQUEST"
        assert 0.5 <= elapsed < 3.0, f"reaped after {elapsed:.2f}s"

    def test_trickled_frame_reaped_at_frame_deadline(self, hostile):
        """Per-recv progress forever, whole-frame progress never: the
        per-frame read deadline (distinct from idleTimeout) reaps it."""
        s, door, oracle = hostile
        sock = _authed(door)
        try:
            sock.sendall(P.FRAME.pack(P.REQ_STATUS, 256, 0))
            t0 = time.monotonic()
            deadline = t0 + 5.0
            out = "hang"
            while time.monotonic() < deadline:
                try:
                    sock.sendall(b"\x00")
                except OSError:
                    break
                out = FW._read_outcome(sock, 0.1)
                if out != "hang":
                    break
            if out == "hang":
                out = FW._read_outcome(sock, 2.0)
            elapsed = time.monotonic() - t0
        finally:
            sock.close()
        assert out == "typed:BAD_REQUEST"
        assert elapsed < 3.0, f"trickle survived {elapsed:.2f}s"


class TestSpecArmor:
    BOMBS = {
        "depth_bomb": {"fuzzer": "spec", "kind": "depth_bomb",
                       "depth": 120},
        "depth_bomb_past_parser": {"fuzzer": "spec",
                                   "kind": "depth_bomb", "depth": 5000},
        "node_bomb": {"fuzzer": "spec", "kind": "node_bomb",
                      "width": 12000},
        "wide_ops": {"fuzzer": "spec", "kind": "wide_ops", "ops": 100},
        "param_bomb": {"fuzzer": "spec", "kind": "param_bomb",
                       "index": 10 ** 9},
        "big_string": {"fuzzer": "spec", "kind": "big_string",
                       "bytes": 70_000},
        "join_bomb": {"fuzzer": "spec", "kind": "join_bomb",
                      "joins": 16},
    }

    @pytest.mark.parametrize("name", sorted(BOMBS))
    def test_resource_bomb_rejected_typed_and_fast(self, hostile, name):
        """Every resource bomb answers BAD_REQUEST in bounded time —
        the planner never recurses past the cap, the evaluator never
        materializes the bomb."""
        s, door, oracle = hostile
        sock = _authed(door)
        try:
            t0 = time.monotonic()
            sock.sendall(FW._frame_bytes(
                P.REQ_SUBMIT, FW._spec_payload(self.BOMBS[name])))
            out = FW._read_outcome(sock, 6.0)
            elapsed = time.monotonic() - t0
        finally:
            sock.close()
        assert out == "typed:BAD_REQUEST", f"{name}: {out}"
        assert elapsed < 2.0, f"{name} took {elapsed:.2f}s"

    def test_validator_names_the_bounding_conf(self):
        limits = SpecLimits()
        deep = ["col", "x"]
        for _ in range(40):
            deep = ["not", deep]
        with pytest.raises(BadSpec, match="spec.maxDepth"):
            validate_spec({"table": "t", "ops": [
                {"op": "filter", "expr": deep}]}, limits)
        with pytest.raises(BadSpec, match="spec.maxOps"):
            validate_spec({"table": "t",
                           "ops": [{"op": "limit", "n": 1}] * 65},
                          limits)
        with pytest.raises(BadSpec, match="spec.maxJoins"):
            validate_spec({"table": "t", "ops": [
                {"op": "join", "table": "u", "on": [["a", "b"]]}] * 9},
                limits)
        with pytest.raises(BadSpec, match="spec.maxParams"):
            validate_spec({"table": "t", "ops": [
                {"op": "filter",
                 "expr": [">", ["col", "x"],
                          ["param", 10 ** 9, "int"]]}]}, limits)
        with pytest.raises(BadSpec, match="spec.maxStringBytes"):
            validate_spec({"table": "t", "ops": [
                {"op": "filter",
                 "expr": ["==", ["col", "x"],
                          ["lit", "x" * 70_000]]}]}, limits)

    def test_validator_passes_the_real_templates(self):
        """The armor must not reject healthy traffic: every loadgen
        template clears the default limits untouched."""
        limits = SpecLimits()
        for name, (spec, _pools) in LG.templates().items():
            validate_spec(spec, limits)

    def test_bomb_never_escapes_to_internal(self, hostile):
        """A depth bomb through the REAL client surfaces BAD_REQUEST —
        never INTERNAL, never a closed socket."""
        s, door, oracle = hostile
        deep = json.loads(
            '["not",' * 100 + '["col","o_amt"]' + "]" * 100)
        with WireClient("127.0.0.1", door.port) as c:
            with pytest.raises(WireError) as ei:
                c.query({"table": "orders", "ops": [
                    {"op": "filter", "expr": deep}]})
        assert ei.value.code == "BAD_REQUEST"
        assert "maxDepth" in str(ei.value)


# ---------------------------------------------------------------------------------
# Leak audits per attack class (the PR 7 discipline, hostile edition)
# ---------------------------------------------------------------------------------

def _attack_oversized_frame(door):
    sock = FW._dial("127.0.0.1", door.port, 6.0)
    try:
        sock.sendall(P.FRAME.pack(P.REQ_SUBMIT, 2 << 30, 0))
        FW._read_outcome(sock, 6.0)
    finally:
        sock.close()


def _attack_strike_budget(door):
    FW.run_frame_case({"case": 0, "fuzzer": "frame",
                       "kind": "strike_burn"},
                      "127.0.0.1", door.port, 6.0)
    _sit_out_penalty_box()


def _attack_slowloris(door):
    sock = FW._dial("127.0.0.1", door.port, 10.0)
    try:
        FW._read_outcome(sock, 6.0)  # reaped at the handshake deadline
    finally:
        sock.close()


def _attack_spec_bomb(door):
    sock = FW._dial("127.0.0.1", door.port, 6.0)
    try:
        sock.sendall(FW._frame_bytes(*FW._base_frame("hello")))
        P.recv_frame(sock, expect=(P.RSP_WELCOME,))
        sock.sendall(FW._frame_bytes(P.REQ_SUBMIT, FW._spec_payload(
            {"fuzzer": "spec", "kind": "depth_bomb", "depth": 2000})))
        FW._read_outcome(sock, 6.0)
    finally:
        sock.close()


class TestHostileCleanup:
    ATTACKS = {"oversized_frame": _attack_oversized_frame,
               "strike_budget": _attack_strike_budget,
               "slowloris": _attack_slowloris,
               "spec_bomb": _attack_spec_bomb}

    @pytest.mark.parametrize("attack", sorted(ATTACKS))
    def test_attack_leaves_no_residue(self, hostile, attack):
        """After each attack class: zero in-flight queries, zero quota
        permits, zero spill leaks — and the door still serves exact
        results."""
        s, door, oracle = hostile
        self.ATTACKS[attack](door)
        assert _await_clean(s, door), f"{attack}: residue"
        assert door.quotas.inflight() == 0
        get_catalog().assert_no_leaks()
        _door_still_serves(door, oracle)


# ---------------------------------------------------------------------------------
# The checked-in corpus replays clean at tier-1
# ---------------------------------------------------------------------------------

class TestCorpusReplay:
    def test_corpus_covers_every_attack_class(self):
        cases = FW.load_corpus(CORPUS)
        kinds = {(c["fuzzer"], c["kind"]) for c in cases}
        for kind, _w in FW.FRAME_KINDS:
            assert ("frame", kind) in kinds, f"corpus misses {kind}"
        for kind, _w in FW.SPEC_KINDS:
            assert ("spec", kind) in kinds, f"corpus misses {kind}"

    def test_corpus_replays_clean(self, hostile):
        """Every checked-in case answered typed (or benign/self-
        closing) — zero hangs, crashes, mismatches, or untyped
        rejections against a live door."""
        s, door, oracle = hostile
        spec_conn = FW.SpecAttacker("127.0.0.1", door.port, 6.0)
        bad = {}
        try:
            for case in FW.load_corpus(CORPUS):
                if case["fuzzer"] == "frame":
                    out = FW.run_frame_case(case, "127.0.0.1",
                                            door.port, 6.0)
                else:
                    out = spec_conn.run_case(case, LG.templates,
                                             LG._norm_rows, oracle)
                if not (out == "ok" or out.startswith("typed:")):
                    bad[f"{case['kind']}#{case['case']}"] = out
                if case["kind"] == "strike_burn":
                    _sit_out_penalty_box()
        finally:
            spec_conn.close()
        assert not bad, f"corpus survivors: {bad}"
        assert _await_clean(s, door)
        get_catalog().assert_no_leaks()
        _door_still_serves(door, oracle)


# ---------------------------------------------------------------------------------
# Satellites: ops caps, injector point, perfwatch record, docs, confs
# ---------------------------------------------------------------------------------

class TestOpsArmor:
    def test_oversized_request_head_rejected(self, hostile):
        """A request head past ``ops.maxRequestBytes`` answers 431 and
        closes — the scrape surface never buffers a hostile head."""
        s, door, oracle = hostile
        sock = socket.create_connection(("127.0.0.1", door.ops_port),
                                        timeout=6.0)
        try:
            sock.sendall(b"GET /metrics HTTP/1.1\r\nX-Junk: "
                         + b"a" * 4096 + b"\r\n\r\n")
            data = sock.recv(4096)
        finally:
            sock.close()
        assert b"431" in data.split(b"\r\n", 1)[0] or data == b""

    def test_slow_request_reaped(self, hostile):
        """A trickled request head is reaped near the ops deadline."""
        s, door, oracle = hostile
        sock = socket.create_connection(("127.0.0.1", door.ops_port),
                                        timeout=6.0)
        t0 = time.monotonic()
        try:
            sock.sendall(b"GET /metr")  # ...and never finish the line
            sock.settimeout(5.0)
            try:
                data = sock.recv(4096)
            except socket.timeout:
                data = b"HUNG"
            elapsed = time.monotonic() - t0
        finally:
            sock.close()
        assert data != b"HUNG", "ops socket survived a slowloris head"
        assert elapsed < 4.0
        # the surface still scrapes
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{door.ops_port}/metrics",
                timeout=5.0) as r:
            assert r.status == 200


class TestSatellites:
    NEW_CONFS = (
        "spark.rapids.tpu.server.maxFrameBytes",
        "spark.rapids.tpu.server.maxControlFrameBytes",
        "spark.rapids.tpu.server.handshakeTimeoutMs",
        "spark.rapids.tpu.server.frameTimeoutMs",
        "spark.rapids.tpu.server.maxDecodeErrors",
        "spark.rapids.tpu.server.penaltyBoxMs",
        "spark.rapids.tpu.server.maxInflightPerConn",
        "spark.rapids.tpu.server.spec.maxDepth",
        "spark.rapids.tpu.server.spec.maxNodes",
        "spark.rapids.tpu.server.spec.maxOps",
        "spark.rapids.tpu.server.spec.maxParams",
        "spark.rapids.tpu.server.spec.maxStringBytes",
        "spark.rapids.tpu.server.spec.maxJoins",
        "spark.rapids.tpu.server.ops.maxRequestBytes",
        "spark.rapids.tpu.server.ops.requestTimeoutMs",
    )

    def test_confs_registered_and_documented(self):
        keys = set(ALL_ENTRIES)
        with open(os.path.join(REPO, "docs", "configs.md")) as f:
            docs = f.read()
        for key in self.NEW_CONFS:
            assert key in keys, f"{key} not registered"
            assert key in docs, f"{key} not in docs/configs.md"

    def test_injector_point_registered(self):
        from spark_rapids_tpu.faults.injector import POINTS
        assert "server.malformed" in POINTS

    def test_hostile_metrics_registered(self):
        from spark_rapids_tpu.utils.telemetry import METRICS
        names = {m[0] for m in METRICS}
        for n in ("server_decode_errors_total",
                  "server_hostile_disconnects_total",
                  "server_penalty_refusals_total",
                  "ops_requests_rejected_total"):
            assert n in names, f"{n} not registered"

    def test_docs_sections_present(self):
        with open(os.path.join(REPO, "docs", "serving.md")) as f:
            serving = f.read()
        assert "Hostile input" in serving
        assert "penalty box" in serving.lower()
        with open(os.path.join(REPO, "docs", "robustness.md")) as f:
            robust = f.read()
        assert "server.malformed" in robust
        assert "fuzzwire" in robust

    def test_bench_exposes_the_fuzz_drill(self):
        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        assert "SRT_BENCH_FUZZ" in src
        assert "fuzz_survival" in src

    def test_perfwatch_gates_fuzz_survival(self, tmp_path):
        """The ``fuzz_survival`` record kind gates ABSOLUTE — it
        passes/fails on an empty ledger, no baseline needed."""
        from tools import perfwatch
        good = {"fuzz_survival": 1, "cases": 200, "crashes": 0,
                "hangs": 0, "untyped_rejections": 0, "leaks": 0,
                "sidecar_mismatches": 0, "goodput_ratio": 1.4,
                "corpus_new": 0}
        run = tmp_path / "fuzz.json"
        ledger = tmp_path / "ledger.jsonl"
        run.write_text(json.dumps(good) + "\n")
        entry = perfwatch.load_run(str(run))
        assert entry["kind"] == "fuzz_survival"
        assert perfwatch.main(["check", str(ledger), str(run)]) == 0
        for field, val in (("crashes", 1), ("hangs", 2),
                           ("untyped_rejections", 3), ("leaks", 1),
                           ("sidecar_mismatches", 1),
                           ("goodput_ratio", 0.5),
                           ("corpus_new", 1), ("cases", 0)):
            bad = dict(good)
            bad[field] = val
            run.write_text(json.dumps(bad) + "\n")
            rc = perfwatch.main(["check", str(ledger), str(run)])
            assert rc == 1, f"{field}={val} passed the gate"

    def test_mini_fuzz_run_survives(self, hostile):
        """A seeded 40-case fuzz leg end-to-end through ``run_fuzz``'s
        case engine against the live door (the full harness with its
        own door + sidecar is the bench drill / acceptance run)."""
        s, door, oracle = hostile
        cases = FW.gen_cases(seed=7, n=40)
        # skip the slow legs here: tier-1 already proves them above
        cases = [c for c in cases if c["kind"] not in (
            "slowloris_handshake", "slowloris_frame", "strike_burn")]
        spec_conn = FW.SpecAttacker("127.0.0.1", door.port, 6.0)
        outcomes = {}
        try:
            for c in cases:
                if c["fuzzer"] == "frame":
                    out = FW.run_frame_case(c, "127.0.0.1", door.port,
                                            6.0)
                else:
                    out = spec_conn.run_case(c, LG.templates,
                                             LG._norm_rows, oracle)
                outcomes[f"{c['kind']}#{c['case']}"] = out
        finally:
            spec_conn.close()
        survivors = {k: v for k, v in outcomes.items()
                     if v in ("hang", "crash", "mismatch")
                     or v.startswith("harness_error")}
        assert not survivors, survivors
        assert _await_clean(s, door)
        get_catalog().assert_no_leaks()
        _door_still_serves(door, oracle)

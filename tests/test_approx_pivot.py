"""approx_percentile (moments sketch) + pivot (conditional aggregation).

Reference: GpuApproximatePercentile.scala (t-digest sketch buffers merged
through the two-phase exchange) and AggregateFunctions.scala PivotFirst.
Here the sketch is a moments sketch (n, Σx..Σx⁴, min, max — every buffer
sum/min/max-reducible, so it merges through the same exchange machinery);
pivot lowers each (value, aggregate) pair to agg(when(p == v, child)).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


class TestApproxPercentile:
    def test_percentile_approx_rank_exact_default(self, sess, rng):
        """percentile_approx keeps the Spark rank contract by defaulting
        to the exact percentile — bimodal data is the case a moments
        estimate gets wrong."""
        t = pa.table({"v": pa.array([0.0] * 500 + [1000.0] * 500)})
        r = sess.create_dataframe(t).agg(
            F.percentile_approx(F.col("v"), 0.25).alias("p")).collect()
        assert r[0][0] == 0.0

    def test_grouped_vs_exact_smooth(self, sess, rng):
        n = 40000
        t = pa.table({"k": pa.array(rng.integers(0, 5, n)),
                      "v": pa.array(rng.normal(100.0, 15.0, n))})
        df = (sess.create_dataframe(t).group_by("k")
              .agg(F.moments_percentile(F.col("v"), 0.5).alias("p50"),
                   F.moments_percentile(F.col("v"), 0.9).alias("p90")))
        got = {r[0]: (r[1], r[2]) for r in df.collect()}
        pdf = t.to_pandas()
        for k, g in pdf.groupby("k"):
            e50 = g.v.quantile(0.5)
            e90 = g.v.quantile(0.9)
            # distributional accuracy: within 5% of the IQR-scale spread
            tol = 0.05 * (g.v.quantile(0.95) - g.v.quantile(0.05))
            assert abs(got[k][0] - e50) < tol, (k, got[k][0], e50)
            assert abs(got[k][1] - e90) < tol, (k, got[k][1], e90)

    def test_ungrouped_and_bounds(self, sess, rng):
        n = 10000
        t = pa.table({"v": pa.array(rng.uniform(0.0, 10.0, n))})
        df = sess.create_dataframe(t).agg(
            F.moments_percentile(F.col("v"), 0.01).alias("lo"),
            F.moments_percentile(F.col("v"), 0.99).alias("hi"))
        lo, hi = df.collect()[0]
        # estimates are clamped to the observed [min, max]
        assert 0.0 <= lo <= 1.0
        assert 9.0 <= hi <= 10.0

    def test_merges_across_batches(self, sess, rng):
        """Small batchSizeRows forces multi-batch partial merges: the
        sketch buffers must combine associatively."""
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 512)
        try:
            n = 8000
            t = pa.table({"k": pa.array(rng.integers(0, 3, n)),
                          "v": pa.array(rng.normal(0.0, 1.0, n))})
            df = (sess.create_dataframe(t).group_by("k")
                  .agg(F.moments_percentile(F.col("v"), 0.5).alias("m")))
            got = {r[0]: r[1] for r in df.collect()}
            pdf = t.to_pandas()
            for k, g in pdf.groupby("k"):
                assert abs(got[k] - g.v.median()) < 0.15
        finally:
            sess.conf.unset("spark.rapids.tpu.sql.batchSizeRows")

    def test_null_and_empty_groups(self, sess):
        t = pa.table({"k": pa.array([1, 1, 2], type=pa.int64()),
                      "v": pa.array([5.0, None, None])})
        df = (sess.create_dataframe(t).group_by("k")
              .agg(F.moments_percentile(F.col("v"), 0.5).alias("m")))
        got = {r[0]: r[1] for r in df.collect()}
        assert got[1] == 5.0
        assert got[2] is None


class TestPivot:
    def test_pivot_sum(self, sess):
        t = pa.table({"g": [1, 1, 2, 2, 2], "p": ["a", "b", "a", "a", "b"],
                      "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
        rows = sorted(sess.create_dataframe(t).group_by("g")
                      .pivot("p", ["a", "b"]).agg(F.sum(F.col("v")))
                      .collect())
        assert rows == [(1, 1.0, 2.0), (2, 7.0, 5.0)]

    def test_pivot_missing_combo_is_null_or_zero(self, sess):
        t = pa.table({"g": [1, 2], "p": ["a", "b"], "v": [1.0, 2.0]})
        rows = sorted(sess.create_dataframe(t).group_by("g")
                      .pivot("p", ["a", "b"]).agg(F.min(F.col("v")))
                      .collect())
        assert rows[0][1] == 1.0 and rows[0][2] is None
        assert rows[1][1] is None and rows[1][2] == 2.0

    def test_pivot_count_star(self, sess):
        t = pa.table({"g": [1, 1, 1, 2], "p": ["a", "a", "b", "b"],
                      "v": [1.0, 2.0, 3.0, 4.0]})
        rows = sorted(sess.create_dataframe(t).group_by("g")
                      .pivot("p", ["a", "b"]).count().collect())
        assert rows == [(1, 2, 1), (2, 0, 1)]

    def test_pivot_multiple_aggs(self, sess):
        t = pa.table({"g": [1, 1, 2], "p": ["a", "b", "a"],
                      "v": [1.0, 2.0, 3.0]})
        df = (sess.create_dataframe(t).group_by("g")
              .pivot("p", ["a", "b"])
              .agg(F.sum(F.col("v")).alias("s"),
                   F.count(F.col("v")).alias("c")))
        assert df.columns == ["g", "a_s", "a_c", "b_s", "b_c"]
        rows = sorted(df.collect())
        assert rows[0] == (1, 1.0, 1, 2.0, 1)
        assert rows[1] == (2, 3.0, 1, None, 0)

    def test_pivot_first_skips_injected_nulls(self, sess):
        """PivotFirst semantics: first() must return the first MATCHING
        row's value, not the NULL injected for non-matching rows."""
        t = pa.table({"g": [1, 1, 1], "p": ["b", "a", "a"],
                      "v": [9.0, 1.0, 2.0]})
        rows = (sess.create_dataframe(t).group_by("g")
                .pivot("p", ["a", "b"]).first("v").collect())
        assert rows == [(1, 1.0, 9.0)]

    def test_pivot_string_values_on_strings(self, sess):
        t = pa.table({"g": ["x", "x", "y"], "p": ["a", "b", "a"],
                      "v": [10, 20, 30]})
        rows = sorted(sess.create_dataframe(t).group_by("g")
                      .pivot("p", ["a", "b"]).agg(F.sum(F.col("v")))
                      .collect())
        assert rows == [("x", 10, 20), ("y", 30, None)]

"""Memory discipline: spillable batches, the spill catalog, OOM retry with
split, and per-operator OOM injection.

Reference model: SpillableColumnarBatchSuite, HashAggregateRetrySuite,
GpuSortRetrySuite, spark.rapids.sql.test.injectRetryOOM
(RapidsConf.scala:1347) — the inject_oom marker pattern from
integration_tests/marks.py."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.batch import from_numpy
from spark_rapids_tpu.memory.retry import (INJECTOR, RetryOOM,
                                           SplitAndRetryOOM, split_in_half,
                                           with_retry)
from spark_rapids_tpu.memory.spill import SpillCatalog, SpillableBatch
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.utils.metrics import TaskMetrics
from .support import assert_rows_equal


@pytest.fixture(autouse=True)
def _disarm():
    INJECTOR.arm(0, 0)
    yield
    INJECTOR.arm(0, 0)


def _batch(n=100):
    return from_numpy({"a": np.arange(n, dtype=np.int64),
                       "b": np.linspace(0, 1, n)})


class TestSpillableBatch:
    def test_roundtrip_host(self, tmp_path):
        cat = SpillCatalog(1 << 30, 1 << 30, str(tmp_path))
        b = _batch()
        sb = cat.register(b)
        assert sb.state == SpillableBatch.DEVICE
        freed = sb.spill_to_host()
        assert freed > 0 and sb.state == SpillableBatch.HOST
        back = sb.get()
        assert sb.state == SpillableBatch.DEVICE
        assert np.array_equal(np.asarray(back.columns[0].data),
                              np.asarray(b.columns[0].data))
        sb.close()

    def test_roundtrip_disk(self, tmp_path):
        cat = SpillCatalog(1 << 30, 1 << 30, str(tmp_path))
        sb = cat.register(_batch())
        sb.spill_to_host()
        freed = sb.spill_to_disk()
        assert freed > 0 and sb.state == SpillableBatch.DISK
        back = sb.get()
        assert back.num_rows == 100
        sb.close()

    def test_budget_triggers_spill(self, tmp_path):
        one = _batch(1000).device_size_bytes()
        cat = SpillCatalog(int(one * 2.5), 1 << 30, str(tmp_path))
        handles = [cat.register(_batch(1000)) for _ in range(4)]
        states = [h.state for h in handles]
        assert states.count(SpillableBatch.HOST) >= 1
        assert cat.device_bytes_in_use() <= cat.device_budget
        assert cat.spilled_device_bytes > 0
        for h in handles:
            h.close()

    def test_host_budget_overflows_to_disk(self, tmp_path):
        one = _batch(1000)
        nbytes = one.device_size_bytes()
        cat = SpillCatalog(nbytes, nbytes, str(tmp_path))
        handles = [cat.register(_batch(1000)) for _ in range(4)]
        assert any(h.state == SpillableBatch.DISK for h in handles)
        for h in handles:
            assert h.get().num_rows == 1000
            h.close()

    def test_priority_orders_spill(self, tmp_path):
        cat = SpillCatalog(1 << 30, 1 << 30, str(tmp_path))
        low = cat.register(_batch(), priority=0)
        high = cat.register(_batch(), priority=5)
        assert cat.spill_one_device()
        assert low.state == SpillableBatch.HOST
        assert high.state == SpillableBatch.DEVICE
        low.close()
        high.close()


class TestWithRetry:
    def test_plain_retry_succeeds(self):
        INJECTOR.arm(1, 0)
        b = _batch(50)
        TaskMetrics.get().reset_counts()
        outs = list(with_retry(None, b, lambda x: x.num_rows))
        assert outs == [50]
        assert TaskMetrics.get().retry_count == 1

    def test_split_and_retry(self):
        INJECTOR.arm(0, 1)
        b = _batch(50)
        TaskMetrics.get().reset_counts()
        outs = list(with_retry(None, b, lambda x: x.num_rows))
        assert sorted(outs) == [25, 25]
        assert TaskMetrics.get().split_retry_count == 1

    def test_retry_escalates_to_split(self):
        # more plain OOMs than MAX_PLAIN_RETRIES -> escalate to split
        INJECTOR.arm(4, 0)
        outs = list(with_retry(None, _batch(40), lambda x: x.num_rows))
        assert sum(outs) == 40 and len(outs) >= 2

    def test_single_row_cannot_split(self):
        with pytest.raises(SplitAndRetryOOM):
            split_in_half(_batch(1))

    def test_split_preserves_rows(self):
        halves = split_in_half(_batch(101))
        assert [h.num_rows for h in halves] == [50, 51]


class TestOperatorOOMInjection:
    """Every device operator must survive injected OOM (the reference's
    retry suites + inject_oom marker)."""

    def _session(self, n_retry=0, n_split=0):
        srt.Session.reset()
        s = srt.Session.get_or_create()
        s.conf.set("spark.rapids.tpu.test.injectRetryOOM", n_retry)
        s.conf.set("spark.rapids.tpu.test.injectSplitAndRetryOOM", n_split)
        return s

    def teardown_method(self, m):
        srt.Session.reset()
        INJECTOR.arm(0, 0)

    def test_filter_project_survives_retry(self):
        s = self._session(n_retry=1)
        df = s.create_dataframe({"a": list(range(100))})
        got = df.where(F.col("a") < 10).select(
            (F.col("a") * 2).alias("x")).collect()
        assert sorted(r[0] for r in got) == [i * 2 for i in range(10)]

    def test_filter_project_survives_split(self):
        s = self._session(n_split=1)
        df = s.create_dataframe({"a": list(range(100))})
        got = df.where(F.col("a") < 10).select(
            (F.col("a") * 2).alias("x")).collect()
        assert sorted(r[0] for r in got) == [i * 2 for i in range(10)]

    def test_grouped_agg_survives_retry_and_split(self):
        s = self._session(n_retry=1, n_split=1)
        pdf = pd.DataFrame({"k": [i % 7 for i in range(500)],
                            "v": np.arange(500, dtype=np.float64)})
        df = s.create_dataframe(pdf)
        got = df.group_by("k").agg(F.sum(F.col("v")).alias("s")).collect()
        expect = [(int(k), float(v)) for k, v in
                  pdf.groupby("k")["v"].sum().items()]
        assert_rows_equal(got, expect)

    def test_ungrouped_agg_survives_split(self):
        s = self._session(n_split=1)
        df = s.create_dataframe({"v": list(range(1000))})
        got = df.agg(F.sum(F.col("v")).alias("s")).collect()
        assert got[0][0] == sum(range(1000))

    def test_join_survives_retry(self):
        s = self._session(n_retry=2)
        l = s.create_dataframe({"k": [1, 2, 3], "a": [1.0, 2.0, 3.0]})
        r = s.create_dataframe({"k": [2, 3, 4], "b": [20.0, 30.0, 40.0]})
        got = l.join(r, on="k", how="inner").collect()
        assert_rows_equal(got, [(2, 2.0, 20.0), (3, 3.0, 30.0)])

    def test_retry_disabled_raises(self):
        s = self._session(n_retry=1)
        s.conf.set("spark.rapids.tpu.memory.retry.enabled", False)
        df = s.create_dataframe({"a": list(range(10))})
        # injector armed but protocol disabled: OOM must propagate...
        # (injection happens inside device_op only when retry is enabled,
        # so with retry disabled the query simply runs)
        got = df.select((F.col("a") + 1).alias("x")).collect()
        assert len(got) == 10


class TestSpillDuringQuery:
    def test_query_over_budget_spills_and_completes(self, tmp_path):
        from spark_rapids_tpu.memory import spill as spill_mod
        spill_mod.reset_catalog()
        srt.Session.reset()
        s = srt.Session.get_or_create()
        try:
            # tiny device budget: accumulated sorted runs must spill to host
            cat = SpillCatalog(40_000, 1 << 30, str(tmp_path))
            spill_mod._catalog = cat
            s.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1000)
            rng = np.random.default_rng(2)
            pdf = pd.DataFrame({"k": rng.integers(0, 10**6, 20_000),
                                "v": rng.uniform(0, 1, 20_000)})
            df = s.create_dataframe(pdf)
            got = df.sort("k").to_pandas()
            assert list(got["k"]) == sorted(pdf["k"])
            assert cat.spill_count > 0, "expected spills under a tiny budget"
        finally:
            spill_mod.reset_catalog()
            srt.Session.reset()

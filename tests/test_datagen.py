"""Datagen DSL + scale harness (bigDataGen.scala analog):
determinism under chunking, distributions, FK integrity, string
patterns, nested generators, multi-file scale writes — and the data is
queryable through the engine."""

import numpy as np
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import datagen as DG
from spark_rapids_tpu.sql import functions as F


def _spec():
    return DG.TableSpec("t", {
        "id": DG.SeqGen(),
        "fk": DG.FKGen(parent_rows=50, distribution="zipf"),
        "v": DG.DoubleGen(lo=0, hi=100, nullable=False),
        "tag": DG.StringGen(pattern="tag-[0-9]{3}", nullable=False),
        "flag": DG.BoolGen(null_prob=0.2),
    })


def test_deterministic_and_chunk_invariant():
    a = _spec().generate(5000, seed=7, chunk=5000)
    b = _spec().generate(5000, seed=7, chunk=512)
    assert a.equals(b)
    c = _spec().generate(5000, seed=8)
    assert not a.equals(c)


def test_seq_and_fk_integrity():
    t = _spec().generate(2000, seed=1)
    ids = t.column("id").to_pylist()
    assert ids == list(range(1, 2001))
    fks = t.column("fk").to_pylist()
    assert min(fks) >= 1 and max(fks) <= 50


def test_zipf_skew_is_skewed():
    t = DG.TableSpec("z", {
        "k": DG.FKGen(parent_rows=1000, distribution="zipf"),
    }).generate(20_000, seed=3)
    import collections
    counts = collections.Counter(t.column("k").to_pylist())
    top = counts.most_common(1)[0][1]
    assert top > 20_000 / 1000 * 10  # hot key far above uniform share

def test_string_pattern():
    import re
    t = DG.TableSpec("s", {
        "x": DG.StringGen(pattern="[A-C]{2}-[0-9]{3,5}", nullable=False),
    }).generate(200, seed=5)
    rx = re.compile(r"^[A-C]{2}-[0-9]{3,5}$")
    assert all(rx.match(s) for s in t.column("x").to_pylist())


def test_nested_and_decimal():
    t = DG.TableSpec("n", {
        "arr": DG.ArrayGen(DG.IntGen(0, 10, nullable=False),
                           max_len=3, nullable=False),
        "st": DG.StructGen({"a": DG.IntGen(0, 5, nullable=False),
                            "b": DG.BoolGen(nullable=False)},
                           nullable=False),
        "d": DG.DecimalGen(10, 2, nullable=False),
    }).generate(100, seed=2)
    assert t.column("arr").type.value_type == "int32"
    assert str(t.column("d").type) == "decimal128(10, 2)"


def test_scale_write_multi_file(tmp_path):
    paths = _spec().write_parquet(str(tmp_path), 10_000, seed=9,
                                  files=4, chunk=1500)
    assert len(paths) == 4
    total = sum(pq.ParquetFile(p).metadata.num_rows for p in paths)
    assert total == 10_000
    # multi-file write matches the in-memory generation exactly
    import pyarrow as pa
    whole = pa.concat_tables([pq.read_table(p) for p in paths])
    assert whole.equals(_spec().generate(10_000, seed=9))


def test_generated_data_queryable(fresh_session, tmp_path):
    sess = fresh_session
    paths = _spec().write_parquet(str(tmp_path), 5000, seed=11, files=2)
    import pyarrow as pa
    whole = pa.concat_tables([pq.read_table(p) for p in paths])
    df = sess.create_dataframe(whole)
    got = dict(df.group_by("fk")
               .agg(F.count_star().alias("c")).collect())
    import collections
    want = collections.Counter(whole.column("fk").to_pylist())
    assert got == dict(want)

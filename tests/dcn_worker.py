"""One rank of a DCN distributed-aggregation run (spawned by test_dcn.py
and the killed-peer chaos suite in test_dcn_failures.py).

Each rank is a real separate process with its own JAX runtime, session, and
input shard — the multi-host execution model, rehearsed on localhost.

Chaos knobs: ``--kill-rank R --kill-after N`` arms the ``dcn.peer_kill``
injection point on rank R only — the rank dies at its Nth reduce-side
shuffle op (mid-shuffle, after its map output committed).
``--kill-mode silent`` stops heartbeating and freezes the peer server,
then LINGERS as a zombie (death is visible to survivors only through
failure detection — the worst case); ``--kill-mode hard`` exits the
process immediately.  ``--hb-interval/--hb-timeout/--wait-timeout``
shrink the liveness horizon so recovery-time bounds are testable.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--data", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--query", default="simple")
    ap.add_argument("--kill-rank", type=int, default=-1)
    ap.add_argument("--kill-after", type=int, default=1)
    ap.add_argument("--kill-mode", default="silent",
                    choices=["silent", "hard"])
    ap.add_argument("--kill-point", default="peer",
                    choices=["peer", "coordinator"],
                    help="peer = dcn.peer_kill (the rank dies); "
                         "coordinator = dcn.coordinator_kill (the rank "
                         "AND the coordinator it hosts die — survivors "
                         "must fail over to the standby)")
    ap.add_argument("--hb-interval", type=float, default=2.0)
    ap.add_argument("--hb-timeout", type=float, default=None)
    ap.add_argument("--wait-timeout", type=float, default=None)
    ap.add_argument("--net-partition", default="",
                    help="faults.net.partition program (e.g. '0+1|2') "
                         "armed on EVERY rank; engages after "
                         "--net-after shuffle ops on each rank")
    ap.add_argument("--net-after", type=int, default=0)
    ap.add_argument("--net-heal-s", type=float, default=0.0,
                    help="heal the fabric this many seconds after the "
                         "run starts; a parked minority rank then "
                         "waits for its heal loop to rejoin and "
                         "records the outcome")
    ap.add_argument("--net-dup-rate", type=float, default=0.0)
    ap.add_argument("--net-reorder-rate", type=float, default=0.0)
    ap.add_argument("--net-seed", type=int, default=0)
    ap.add_argument("--quorum-window-ms", type=float, default=None)
    ap.add_argument("--await-parked", default="",
                    help="comma rank list: after finishing, keep this "
                         "rank (and any coordinator it hosts) alive "
                         "until every listed rank wrote its parked "
                         "marker — the minority's heal-and-rejoin "
                         "needs a living coordinator to rejoin to")
    args = ap.parse_args()

    # force the CPU platform the same way tests/conftest.py does — a TPU
    # plugin registered by sitecustomize must not capture this worker
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.parallel.dcn import (Coordinator,
                                               CoordinatorLostError,
                                               PeerLostError, ProcessGroup,
                                               run_distributed_agg)
    from spark_rapids_tpu.sql import functions as F

    if args.hb_timeout is not None:
        TpuConf.set_session("spark.rapids.tpu.dcn.heartbeatTimeout",
                            args.hb_timeout)
    if args.wait_timeout is not None:
        TpuConf.set_session("spark.rapids.tpu.dcn.waitTimeout",
                            args.wait_timeout)
    if args.quorum_window_ms is not None:
        TpuConf.set_session("spark.rapids.tpu.dcn.quorum.windowMs",
                            args.quorum_window_ms)
    if args.net_partition or args.net_dup_rate or args.net_reorder_rate:
        # every rank arms the SAME link-fault program (each enforces
        # its own side); afterOps makes a cut engage mid-query,
        # deterministically, once this rank has counted N shuffle ops
        TpuConf.set_session("spark.rapids.tpu.faults.net.partition",
                            args.net_partition)
        TpuConf.set_session("spark.rapids.tpu.faults.net.afterOps",
                            args.net_after)
        TpuConf.set_session("spark.rapids.tpu.faults.net.dup.rate",
                            args.net_dup_rate)
        TpuConf.set_session("spark.rapids.tpu.faults.net.reorder.rate",
                            args.net_reorder_rate)
        TpuConf.set_session("spark.rapids.tpu.faults.net.seed",
                            args.net_seed)
        from spark_rapids_tpu.faults.netfabric import FABRIC
        FABRIC.arm(partition=args.net_partition,
                   after_ops=args.net_after,
                   dup_rate=args.net_dup_rate,
                   reorder_rate=args.net_reorder_rate,
                   seed=args.net_seed)
        if args.net_heal_s > 0:
            import threading
            threading.Timer(args.net_heal_s, FABRIC.heal).start()  # ctx-ok (chaos-harness timer, not per-query work)

    coord = None
    if args.rank == 0:
        coord = Coordinator(args.world, port=args.port)
    pg = ProcessGroup(args.rank, args.world, ("127.0.0.1", args.port),
                      coordinator=coord,
                      heartbeat_interval=args.hb_interval)
    try:
        sess = srt.Session.get_or_create()
        if args.kill_rank == args.rank:
            # deterministic kill: THIS rank dies at its Nth reduce-side
            # shuffle op (the dcn.peer_kill / dcn.coordinator_kill
            # injection point; re-armed from conf at every ExecContext
            # like any schedule).  The coordinator point additionally
            # takes the coordinator this rank hosts down with it.
            point = ("dcn.coordinator_kill"
                     if args.kill_point == "coordinator"
                     else "dcn.peer_kill")
            sess.conf.set("spark.rapids.tpu.faults.inject.schedule",
                          f"{point}:{args.kill_after}")
            sess.conf.set("spark.rapids.tpu.dcn.kill.mode", args.kill_mode)
        df = sess.read_parquet(
            os.path.join(args.data, f"part-{args.rank}.parquet"))
        if args.query == "simple":
            q = df.group_by("k", "s").agg(
                F.sum(F.col("v")).alias("sv"),
                F.count_star().alias("c"),
                F.avg(F.col("w")).alias("aw"))
        elif args.query == "topk":
            q = (df.group_by("k")
                 .agg(F.sum(F.col("v")).alias("sv"))
                 .sort(F.col("sv").desc())
                 .limit(3))
        elif args.query == "join":
            # distributed shuffled join + aggregate: both sides sharded.
            # keep the SHUFFLED path under test (the tiny dim would
            # otherwise auto-broadcast)
            sess.conf.set(
                "spark.rapids.tpu.sql.autoBroadcastJoinThreshold", -1)
            dim = sess.read_parquet(
                os.path.join(args.data, f"dim-{args.rank}.parquet"))
            q = (df.join(dim, on=[("k", "dk")])
                 .group_by("dname")
                 .agg(F.sum(F.col("v")).alias("sv"),
                      F.count_star().alias("c"))
                 .sort("dname"))
        elif args.query == "bjoin":
            # broadcast join over DCN: the sharded dim all-gathers so every
            # rank probes its fact shard against the COMPLETE build table
            dim = sess.read_parquet(
                os.path.join(args.data, f"dim-{args.rank}.parquet"))
            q = (df.join(F.broadcast(dim), on=[("k", "dk")])
                 .group_by("dname")
                 .agg(F.sum(F.col("v")).alias("sv"),
                      F.count_star().alias("c"))
                 .sort("dname"))
        else:
            raise SystemExit(f"unknown query {args.query!r}")
        try:
            rows = run_distributed_agg(q, pg)
        except PeerLostError:
            if args.kill_rank == args.rank and args.kill_mode == "silent":
                # silently-killed rank: linger as a zombie (heartbeats
                # stopped, peer server frozen) so survivors must detect
                # the death through the liveness machinery, never
                # through this process exiting.  The test reaps us.
                time.sleep(300)  # fault-ok (simulated wedged rank, not a retry)
                os._exit(143)
            raise
        except Exception as e:
            from spark_rapids_tpu.faults.recovery import QueryFaulted
            from spark_rapids_tpu.parallel.dcn import QuorumLostError
            quorum_park = isinstance(e, QuorumLostError) or (
                isinstance(e, QueryFaulted)
                and ("Quorum" in str(e)
                     or any("QuorumLostError" in r.error
                            for r in e.history)))
            if not (args.net_partition and quorum_park):
                raise
            # minority side of the partition: the park must be TYPED
            # (never a hang, never wrong rows).  Record it; with a heal
            # scheduled, wait for the heal loop to re-register and
            # record the rejoin too.
            marker = {"rank": args.rank, "error": type(e).__name__,
                      "parked": True, "rejoined": False}
            if args.net_heal_s > 0:
                deadline = time.monotonic() + 120
                while pg.quorum_lost and time.monotonic() < deadline:
                    time.sleep(0.1)  # fault-ok (harness poll for the heal loop's rejoin, not a retry)
                marker["rejoined"] = not pg.quorum_lost
                marker["epoch"] = pg.epoch
                marker["inc"] = pg.inc
                marker["coord_rank"] = pg.coord_rank
            with open(f"{args.out}.parked.{args.rank}", "w") as f:
                json.dump(marker, f)
            return
        with open(f"{args.out}.{args.rank}", "w") as f:
            json.dump(rows, f, default=str)
        # recovery accounting rides a sidecar so the chaos suite can
        # assert WHERE the survival came from (remote re-pulls, re-owned
        # partitions) without changing the result-file contract
        from spark_rapids_tpu.utils.metrics import QueryStats
        snap = QueryStats.process().snapshot()
        with open(f"{args.out}.stats.{args.rank}", "w") as f:
            json.dump({**{k: snap[k] for k in
                          ("peers_lost", "fragments_recomputed",
                           "fragments_recomputed_remote",
                           "partitions_reowned", "transient_retries",
                           "coordinator_failovers", "frames_deduped",
                           "quorum_losses", "rank_rejoins")},
                       # epoch continuity is part of the failover
                       # acceptance: survivors must agree on a bumped
                       # epoch after the takeover
                       "final_epoch": pg.epoch,
                       "coord_rank": pg.coord_rank}, f)
        if args.net_dup_rate or args.net_reorder_rate:
            # the dup/reorder differential's zero-leak gate: every
            # spill handle released despite duplicated deliveries
            from spark_rapids_tpu.memory.spill import get_catalog
            get_catalog().assert_no_leaks()
        if args.await_parked:
            ranks = [int(x) for x in args.await_parked.split(",") if x]
            deadline = time.monotonic() + 150
            while time.monotonic() < deadline and not all(
                    os.path.exists(f"{args.out}.parked.{r}")
                    for r in ranks):
                time.sleep(0.2)  # fault-ok (harness wait for the parked peers' heal outcome, not a retry)
        try:
            pg.barrier(allow_shrunk=True)  # outputs durable before exit
        except (PeerLostError, CoordinatorLostError):
            # best-effort exit sync: our own output file is already
            # durable; a peer that exited (closing the rank-0
            # coordinator) or died during this last barrier cannot
            # invalidate it
            pass
    finally:
        pg.close()


if __name__ == "__main__":
    main()

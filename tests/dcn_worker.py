"""One rank of a DCN distributed-aggregation run (spawned by test_dcn.py).

Each rank is a real separate process with its own JAX runtime, session, and
input shard — the multi-host execution model, rehearsed on localhost.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--data", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--query", default="simple")
    args = ap.parse_args()

    # force the CPU platform the same way tests/conftest.py does — a TPU
    # plugin registered by sitecustomize must not capture this worker
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.parallel.dcn import (Coordinator, ProcessGroup,
                                               run_distributed_agg)
    from spark_rapids_tpu.sql import functions as F

    coord = None
    if args.rank == 0:
        coord = Coordinator(args.world, port=args.port)
    pg = ProcessGroup(args.rank, args.world, ("127.0.0.1", args.port),
                      coordinator=coord)
    try:
        sess = srt.Session.get_or_create()
        df = sess.read_parquet(
            os.path.join(args.data, f"part-{args.rank}.parquet"))
        if args.query == "simple":
            q = df.group_by("k", "s").agg(
                F.sum(F.col("v")).alias("sv"),
                F.count_star().alias("c"),
                F.avg(F.col("w")).alias("aw"))
        elif args.query == "topk":
            q = (df.group_by("k")
                 .agg(F.sum(F.col("v")).alias("sv"))
                 .sort(F.col("sv").desc())
                 .limit(3))
        elif args.query == "join":
            # distributed shuffled join + aggregate: both sides sharded.
            # keep the SHUFFLED path under test (the tiny dim would
            # otherwise auto-broadcast)
            sess.conf.set(
                "spark.rapids.tpu.sql.autoBroadcastJoinThreshold", -1)
            dim = sess.read_parquet(
                os.path.join(args.data, f"dim-{args.rank}.parquet"))
            q = (df.join(dim, on=[("k", "dk")])
                 .group_by("dname")
                 .agg(F.sum(F.col("v")).alias("sv"),
                      F.count_star().alias("c"))
                 .sort("dname"))
        elif args.query == "bjoin":
            # broadcast join over DCN: the sharded dim all-gathers so every
            # rank probes its fact shard against the COMPLETE build table
            dim = sess.read_parquet(
                os.path.join(args.data, f"dim-{args.rank}.parquet"))
            q = (df.join(F.broadcast(dim), on=[("k", "dk")])
                 .group_by("dname")
                 .agg(F.sum(F.col("v")).alias("sv"),
                      F.count_star().alias("c"))
                 .sort("dname"))
        else:
            raise SystemExit(f"unknown query {args.query!r}")
        rows = run_distributed_agg(q, pg)
        with open(f"{args.out}.{args.rank}", "w") as f:
            json.dump(rows, f, default=str)
        pg.barrier()  # all outputs durable before any rank exits
    finally:
        pg.close()


if __name__ == "__main__":
    main()

"""Device manager, task semaphore, and df.cache() materialization
(GpuDeviceManager / GpuSemaphore / InMemoryTableScan analogs)."""

import threading

import pyarrow as pa
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_device_manager_initialized(session):
    from spark_rapids_tpu.runtime.device import DeviceManager
    info = DeviceManager.info()
    assert info is not None
    assert session.device is info.device
    assert info.platform in ("cpu", "tpu")


def test_semaphore_bounds_concurrency(session):
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    sem = TpuSemaphore(2)
    active, peak = [0], [0]
    lock = threading.Lock()

    def work():
        with sem.acquire():
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            import time
            time.sleep(0.02)
            with lock:
                active[0] -= 1

    ts = [threading.Thread(target=work) for _ in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert peak[0] <= 2


def test_semaphore_wait_metric(session):
    from spark_rapids_tpu.utils.metrics import TaskMetrics
    TaskMetrics.reset()
    session.create_dataframe({"a": [1, 2]}).collect()
    # any successful collect records a (possibly ~zero) semaphore wait
    assert TaskMetrics.get().semaphore_wait_s >= 0.0


def test_cache_materializes_once(session):
    f = F()
    calls = [0]
    import spark_rapids_tpu.plan.logical as L
    from spark_rapids_tpu.batch import Field, Schema
    from spark_rapids_tpu import types as T

    def factory():
        calls[0] += 1
        yield pa.table({"x": pa.array([1.0, 2.0, 3.0, 4.0])})

    from spark_rapids_tpu.sql.dataframe import DataFrame
    node = L.LogicalScan(Schema([Field("x", T.FLOAT64, True)]),
                         factory, "counting")
    df = DataFrame(node, session).cache()
    a = df.agg(f.sum(f.col("x")).alias("s")).collect()
    b = df.agg(f.count(f.col("x")).alias("n")).collect()
    c = df.filter(f.col("x") > 2.0).collect()
    assert a[0][0] == 10.0 and b[0][0] == 4 and len(c) == 2
    assert calls[0] == 1  # scan ran exactly once

    df.unpersist()
    d = df.agg(f.sum(f.col("x")).alias("s")).collect()
    assert d[0][0] == 10.0
    assert calls[0] == 2  # re-materialized after unpersist


def test_cache_with_strings(session):
    df = session.create_dataframe(
        {"s": ["a", "b", None, "a"], "v": [1, 2, 3, 4]}).cache()
    assert sorted(df.collect(), key=str) == sorted(
        [("a", 1), ("b", 2), (None, 3), ("a", 4)], key=str)
    assert len(df.distinct().collect()) == 4

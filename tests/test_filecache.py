"""Device-tier file cache: hit path correctness, isolation, OOM clearing.

Reference model: filecache.md (decoded-file cache) + the keep-batches-
resident idea of RapidsShuffleInternalManagerBase.scala:897; the OOM
interplay mirrors DeviceMemoryEventHandler.onAllocFailure freeing every
non-catalog reference it can reach.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.io.filecache import (clear_file_cache,
                                           get_device_cache, get_file_cache)
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def pq_file(tmp_path):
    pdf = pd.DataFrame({
        "a": np.arange(1000, dtype=np.int64),
        "b": np.linspace(0.0, 1.0, 1000),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), path)
    return path, pdf


def _cached_session():
    s = srt.Session.get_or_create()
    s.conf.set("spark.rapids.tpu.sql.fileCache.enabled", True)
    s.conf.set("spark.rapids.tpu.sql.fileCache.deviceTier", True)
    return s


def test_device_cache_hit_same_results(pq_file):
    path, pdf = pq_file
    clear_file_cache()
    s = _cached_session()
    try:
        df = s.read_parquet(path)
        q = lambda: df.select((F.col("a") * 2).alias("x")).collect()
        first = q()
        cache = get_device_cache(1 << 30)
        assert cache.hits + cache.misses > 0, "device tier never consulted"
        second = q()
        assert cache.hits > 0, "second scan should hit the device tier"
        assert [tuple(r) for r in first] == [tuple(r) for r in second]
        expected = [(int(a) * 2,) for a in pdf["a"]]
        assert [tuple(r) for r in second] == expected
    finally:
        s.conf.set("spark.rapids.tpu.sql.fileCache.enabled", False)
        clear_file_cache()


def test_device_cache_entries_isolated_from_consumers(pq_file):
    """A filter narrowing one query's selection must not leak into the
    cached batches another query will receive."""
    path, pdf = pq_file
    clear_file_cache()
    s = _cached_session()
    try:
        df = s.read_parquet(path)
        filtered = df.filter(F.col("a") < 10).select("a").collect()
        assert len(filtered) == 10
        full = df.select("a").collect()
        assert len(full) == len(pdf)
    finally:
        s.conf.set("spark.rapids.tpu.sql.fileCache.enabled", False)
        clear_file_cache()


def test_device_cache_cleared_on_oom_path(pq_file):
    """device_op's OOM handler must drop HBM-cached scan batches — they are
    invisible to the spill catalog, so spilling alone cannot free them."""
    path, _ = pq_file
    clear_file_cache()
    s = _cached_session()
    try:
        df = s.read_parquet(path)
        df.select("a").collect()  # populate
        cache = get_device_cache(1 << 30)
        assert cache._bytes > 0

        class FakeOOM(RuntimeError):
            pass

        FakeOOM.__name__ = "XlaRuntimeError"

        from spark_rapids_tpu.memory.retry import RetryOOM, device_op

        def boom():
            raise FakeOOM("RESOURCE_EXHAUSTED: out of memory")

        with pytest.raises(RetryOOM):
            device_op(None, boom)
        assert cache._bytes == 0, "OOM path must clear the device tier"
    finally:
        s.conf.set("spark.rapids.tpu.sql.fileCache.enabled", False)
        clear_file_cache()


def test_stale_file_invalidates(pq_file, tmp_path):
    """Rewriting the file (new mtime/size) must miss the old entry."""
    path, pdf = pq_file
    clear_file_cache()
    s = _cached_session()
    try:
        df = s.read_parquet(path)
        r1 = df.agg(F.sum(F.col("a"))).collect()[0][0]
        assert r1 == int(pdf["a"].sum())
        pdf2 = pd.DataFrame({"a": np.arange(10, dtype=np.int64),
                             "b": np.zeros(10)})
        import os
        import time
        time.sleep(0.01)
        pq.write_table(pa.Table.from_pandas(pdf2, preserve_index=False), path)
        os.utime(path)
        df2 = s.read_parquet(path)
        r2 = df2.agg(F.sum(F.col("a"))).collect()[0][0]
        assert r2 == int(pdf2["a"].sum())
    finally:
        s.conf.set("spark.rapids.tpu.sql.fileCache.enabled", False)
        clear_file_cache()

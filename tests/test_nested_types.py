"""Nested types v1: ARRAY/STRUCT columns as data + collection/JSON exprs.

Reference parity: complexTypeCreator.scala (array/struct creators),
complexTypeExtractors.scala (GetArrayItem/GetStructField/ElementAt),
collectionOperations.scala (size/sort_array/array_* ops),
GpuGetJsonObject.scala and GpuJsonToStructs.scala (JSON expressions).
Nested columns ride as host arrow columns; expressions evaluate through
the host-lowering machinery (plan/stringpred.py) inside device stages.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def _list_table(tmp_path):
    t = pa.table({
        "id": pa.array([1, 2, 3, 4], type=pa.int64()),
        "xs": pa.array([[3, 1, 2], [], None, [5, None, 5]],
                       type=pa.list_(pa.int64())),
        "v": pa.array([1.5, 2.5, 3.5, 4.5]),
    })
    p = os.path.join(str(tmp_path), "lists.parquet")
    pq.write_table(t, p)
    return p


class TestArrayColumns:
    def test_parquet_roundtrip_query_collect(self, sess, tmp_path):
        p = _list_table(tmp_path)
        df = sess.read_parquet(p)
        rows = df.collect()
        assert rows[0][1] == [3, 1, 2]
        assert rows[2][1] is None
        assert rows[3][1] == [5, None, 5]

    def test_write_array_column(self, sess, tmp_path):
        p = _list_table(tmp_path)
        out = os.path.join(str(tmp_path), "out.parquet")
        sess.read_parquet(p).write.parquet(out)
        back = pq.read_table(out)
        assert back.column("xs").to_pylist() == [[3, 1, 2], [], None,
                                                 [5, None, 5]]

    def test_size_element_at(self, sess, tmp_path):
        df = sess.read_parquet(_list_table(tmp_path))
        rows = df.select(
            F.col("id"),
            F.size(F.col("xs")).alias("n"),
            F.element_at(F.col("xs"), F.lit(1)).alias("e1"),
            F.element_at(F.col("xs"), F.lit(-1)).alias("em1"),
        ).collect()
        assert [r[1] for r in rows] == [3, 0, -1, 3]  # size(NULL) = -1
        assert [r[2] for r in rows] == [3, None, None, 5]
        assert [r[3] for r in rows] == [2, None, None, 5]

    def test_get_item_zero_based(self, sess, tmp_path):
        df = sess.read_parquet(_list_table(tmp_path))
        rows = df.select(F.col("xs").getItem(0).alias("x0")).collect()
        assert [r[0] for r in rows] == [3, None, None, 5]

    def test_sort_distinct_min_max_position(self, sess, tmp_path):
        df = sess.read_parquet(_list_table(tmp_path))
        rows = df.select(
            F.sort_array(F.col("xs")).alias("s"),
            F.array_distinct(F.col("xs")).alias("d"),
            F.array_min(F.col("xs")).alias("mn"),
            F.array_max(F.col("xs")).alias("mx"),
            F.array_position(F.col("xs"), F.lit(5)).alias("p"),
        ).collect()
        assert rows[0][0] == [1, 2, 3]
        assert rows[3][0] == [None, 5, 5]  # nulls first ascending
        assert rows[3][1] == [5, None]
        assert rows[0][2] == 1 and rows[0][3] == 3
        assert rows[1][2] is None  # empty → null min
        assert rows[3][4] == 1
        assert rows[0][4] == 0     # absent → 0

    def test_array_contains_three_valued(self, sess):
        t = pa.table({"xs": pa.array([[1, 2], [1, None], None],
                                     type=pa.list_(pa.int64()))})
        df = sess.create_dataframe(t)
        rows = df.select(
            F.array_contains(F.col("xs"), F.lit(2)).alias("c2")).collect()
        assert rows[0][0] is True
        assert rows[1][0] is None   # not found + array has null → NULL
        assert rows[2][0] is None   # null array → NULL

    def test_slice_flatten_join_setops(self, sess):
        t = pa.table({
            "xs": pa.array([[1, 2, 3, 4]], type=pa.list_(pa.int64())),
            "ys": pa.array([[3, 4, 5]], type=pa.list_(pa.int64())),
            "nested": pa.array([[[1, 2], [3]]],
                               type=pa.list_(pa.list_(pa.int64()))),
        })
        df = sess.create_dataframe(t)
        r = df.select(
            F.slice(F.col("xs"), F.lit(2), F.lit(2)).alias("sl"),
            F.flatten(F.col("nested")).alias("fl"),
            F.array_join(F.col("xs"), "-").alias("j"),
            F.array_union(F.col("xs"), F.col("ys")).alias("u"),
            F.array_intersect(F.col("xs"), F.col("ys")).alias("i"),
            F.array_except(F.col("xs"), F.col("ys")).alias("e"),
        ).collect()[0]
        assert r[0] == [2, 3]
        assert r[1] == [1, 2, 3]
        assert r[2] == "1-2-3-4"
        assert r[3] == [1, 2, 3, 4, 5]
        assert r[4] == [3, 4]
        assert r[5] == [1, 2]

    def test_creator_from_device_columns(self, sess):
        t = pa.table({"a": [1, 2, None], "b": [10, 20, 30]})
        df = sess.create_dataframe(t)
        rows = df.select(F.array(F.col("a"), F.col("b")).alias("arr"),
                         F.col("b")).collect()
        assert rows[0][0] == [1, 10]
        assert rows[2][0] == [None, 30]  # null element kept

    def test_filter_on_size_fuses_as_extras(self, sess, tmp_path):
        """size() is a device-typed output over a host-carried ref: it
        lowers to a precomputed extras column inside the fused stage."""
        df = sess.read_parquet(_list_table(tmp_path))
        rows = df.filter(F.size(F.col("xs")) > 0).select(F.col("id")) \
                 .collect()
        assert [r[0] for r in rows] == [1, 4]

    def test_explode_created_array(self, sess):
        t = pa.table({"a": [1, 2], "b": [10, 20]})
        df = sess.create_dataframe(t)
        arr = df.select(F.col("a"),
                        F.array(F.col("a"), F.col("b")).alias("arr"))
        rows = arr.explode("arr", "x").select(F.col("a"), F.col("x")) \
                  .collect()
        assert sorted(rows) == [(1, 1), (1, 10), (2, 2), (2, 20)]

    def test_collect_list_then_element_at(self, sess, rng):
        t = pa.table({"k": pa.array([1, 1, 2, 2, 2], type=pa.int64()),
                      "v": pa.array([5, 6, 7, 8, 9], type=pa.int64())})
        agg = (sess.create_dataframe(t).group_by("k")
               .agg(F.collect_list(F.col("v")).alias("vs")))
        rows = agg.select(F.col("k"), F.size(F.col("vs")).alias("n"),
                          F.sort_array(F.col("vs")).alias("s")).collect()
        m = {r[0]: (r[1], r[2]) for r in rows}
        assert m[1] == (2, [5, 6])
        assert m[2] == (3, [7, 8, 9])


class TestStructColumns:
    def test_struct_create_get_field(self, sess):
        t = pa.table({"a": [1, 2, None], "s": ["x", None, "z"]})
        df = sess.create_dataframe(t)
        st = df.select(F.struct(F.col("a"), F.col("s")).alias("st"))
        rows = st.collect()
        assert rows[0][0] == {"a": 1, "s": "x"}
        assert rows[1][0] == {"a": 2, "s": None}
        back = st.select(F.col("st").getField("a").alias("a"),
                         F.col("st").getItem("s").alias("s")).collect()
        assert back == [(1, "x"), (2, None), (None, "z")]

    def test_struct_parquet_roundtrip(self, sess, tmp_path):
        t = pa.table({
            "id": pa.array([1, 2], type=pa.int64()),
            "st": pa.array([{"x": 1, "y": "a"}, None],
                           type=pa.struct([("x", pa.int64()),
                                           ("y", pa.string())])),
        })
        p = os.path.join(str(tmp_path), "st.parquet")
        pq.write_table(t, p)
        df = sess.read_parquet(p)
        rows = df.select(F.col("id"),
                         F.col("st").getField("x").alias("x")).collect()
        assert rows == [(1, 1), (2, None)]

    def test_get_field_feeds_device_compute(self, sess):
        """st.x + 1 — the extractor output is device-typed, so arithmetic
        over it fuses into the stage via the extras path."""
        t = pa.table({"a": [1, 2, 3], "s": ["u", "v", "w"]})
        df = sess.create_dataframe(t)
        st = df.select(F.struct(F.col("a"), F.col("s")).alias("st"))
        rows = st.select(
            (F.col("st").getField("a") + 1).alias("a1")).collect()
        assert [r[0] for r in rows] == [2, 3, 4]


class TestJson:
    def test_get_json_object(self, sess):
        t = pa.table({"j": ['{"a":1,"b":{"c":"hi"},"xs":[10,20]}',
                            '{"a":2}', "notjson", None]})
        df = sess.create_dataframe(t)
        rows = df.select(
            F.get_json_object(F.col("j"), "$.a").alias("a"),
            F.get_json_object(F.col("j"), "$.b.c").alias("c"),
            F.get_json_object(F.col("j"), "$.xs[1]").alias("x1"),
            F.get_json_object(F.col("j"), "$.b").alias("b"),
        ).collect()
        assert rows[0] == ("1", "hi", "20", '{"c":"hi"}')
        assert rows[1] == ("2", None, None, None)
        assert rows[2] == (None, None, None, None)
        assert rows[3] == (None, None, None, None)

    def test_from_json_struct_and_to_json(self, sess):
        schema = T.struct([("a", T.INT64), ("c", T.STRING)])
        t = pa.table({"j": ['{"a":1,"c":"x"}', '{"a":"bad"}', "zzz"]})
        df = sess.create_dataframe(t)
        rows = df.select(F.from_json(F.col("j"), schema).alias("st")) \
                 .collect()
        assert rows[0][0] == {"a": 1, "c": "x"}
        assert rows[1][0] == {"a": None, "c": None}
        assert rows[2][0] is None
        rows2 = df.select(F.to_json(
            F.from_json(F.col("j"), schema)).alias("js")).collect()
        assert rows2[0][0] == '{"a":1,"c":"x"}'

    def test_get_json_object_wildcard(self, sess):
        t = pa.table({"j": ['{"a":[{"b":1},{"b":2}]}', '{"a":[]}']})
        df = sess.create_dataframe(t)
        rows = df.select(
            F.get_json_object(F.col("j"), "$.a[*].b").alias("bs")).collect()
        assert rows[0][0] == "[1,2]"
        assert rows[1][0] is None

    def test_from_json_array_schema(self, sess):
        schema = T.array(T.INT64)
        t = pa.table({"j": ["[1,2,3]", "{}"]})
        df = sess.create_dataframe(t)
        rows = df.select(F.from_json(F.col("j"), schema).alias("xs"),
                         ).collect()
        assert rows[0][0] == [1, 2, 3]
        assert rows[1][0] is None

"""Scan pushdown: column pruning, predicate extraction, row-group pruning,
file cache (GpuParquetScan.scala:655-661 / GpuMultiFileReader.scala:431 /
filecache.md analogs)."""

import datetime
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.io.parquet import ParquetSource, prune_row_groups
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.pushdown import extract_predicates, optimize_scans
from spark_rapids_tpu.sql import functions as F


@pytest.fixture(scope="module")
def pq_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("pushdown")
    path = str(d / "data.parquet")
    n = 10_000
    rng = np.random.default_rng(7)
    t = pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "b": pa.array(rng.uniform(0, 1, n)),
        "c": pa.array([f"s{i % 100}" for i in range(n)]),
        "d": pa.array(np.arange(n, dtype=np.int32) % 500),
    })
    # small row groups so pruning has something to cut
    pq.write_table(t, path, row_group_size=1000)
    return path


class TestColumnPruning:
    def test_scan_narrowed_to_referenced_columns(self, session, pq_path):
        df = session.read_parquet(pq_path)
        plan = optimize_scans(
            df.select((F.col("a") + 1).alias("x"))._plan)
        scan = plan
        while scan.children:
            scan = scan.children[0]
        assert scan.schema().names() == ["a"]

    def test_filter_columns_survive_pruning(self, session, pq_path):
        df = session.read_parquet(pq_path)
        q = df.where(F.col("b") > 0.5).select("a")
        plan = optimize_scans(q._plan)
        scan = plan
        while scan.children:
            scan = scan.children[0]
        assert set(scan.schema().names()) == {"a", "b"}
        out = q.to_pandas()
        assert list(out.columns) == ["a"]

    def test_count_star_keeps_one_column(self, session, pq_path):
        df = session.read_parquet(pq_path)
        assert df.count() == 10_000

    def test_agg_pruned_result_correct(self, session, pq_path):
        df = session.read_parquet(pq_path)
        out = df.group_by("d").agg(F.sum(F.col("a")).alias("s")).to_pandas()
        pdf = pq.read_table(pq_path).to_pandas()
        expect = pdf.groupby("d")["a"].sum()
        got = dict(zip(out["d"], out["s"]))
        assert len(got) == 500
        assert all(got[k] == expect[k] for k in expect.index)


class TestPredicateExtraction:
    def test_simple_compare(self):
        preds = extract_predicates((F.col("a") > 5).expr)
        assert preds == [("a", ">", 5)]

    def test_conjunction(self):
        cond = ((F.col("a") > 5) & (F.col("b") <= 1.5)).expr
        assert extract_predicates(cond) == [("a", ">", 5), ("b", "<=", 1.5)]

    def test_flipped_literal(self):
        from spark_rapids_tpu import exprs as E
        cond = E.LessThan(E.Literal(5), E.UnresolvedColumn("a"))
        assert extract_predicates(cond) == [("a", ">", 5)]

    def test_disjunction_not_pushed(self):
        cond = ((F.col("a") > 5) | (F.col("b") <= 1.5)).expr
        assert extract_predicates(cond) == []

    def test_in_and_isnotnull(self):
        assert extract_predicates(F.col("a").isin([1, 2]).expr) == [
            ("a", "in", [1, 2])]
        assert extract_predicates(F.col("a").is_not_null().expr) == [
            ("a", "isnotnull", None)]


class TestRowGroupPruning:
    def test_prunes_by_stats(self, pq_path):
        pf = pq.ParquetFile(pq_path)
        # column a is sorted 0..9999, 1000 rows per group
        kept = prune_row_groups(pf, [("a", ">=", 8000)])
        assert kept == [8, 9]
        kept = prune_row_groups(pf, [("a", "<", 1500)])
        assert kept == [0, 1]
        kept = prune_row_groups(pf, [("a", "==", 4500)])
        assert kept == [4]

    def test_no_stats_match_keeps_all(self, pq_path):
        pf = pq.ParquetFile(pq_path)
        kept = prune_row_groups(pf, [("b", ">=", 0.0)])
        assert len(kept) == 10

    def test_contradiction_prunes_all(self, pq_path):
        pf = pq.ParquetFile(pq_path)
        assert prune_row_groups(pf, [("a", ">", 10**9)]) == []

    def test_query_result_with_pruning(self, session, pq_path):
        df = session.read_parquet(pq_path)
        out = df.where(F.col("a") >= 9995).select("a").to_pandas()
        assert sorted(out["a"]) == [9995, 9996, 9997, 9998, 9999]

    def test_pruned_empty_result(self, session, pq_path):
        df = session.read_parquet(pq_path)
        out = df.where(F.col("a") > 10**9).select("a").to_pandas()
        assert out is None or len(out) == 0


class TestFileCache:
    def test_cache_hit_same_result(self, pq_path):
        from spark_rapids_tpu.io import filecache
        filecache.clear_file_cache()
        src = ParquetSource(pq_path, columns=["a"], cache_bytes=1 << 30)
        t1 = list(src())
        t2 = list(src())
        assert sum(t.num_rows for t in t1) == sum(t.num_rows for t in t2)
        cache = filecache.get_file_cache(1 << 30)
        assert cache.hits >= 1

    def test_cache_disabled_by_default(self, session, pq_path):
        df = session.read_parquet(pq_path)
        src = df._plan.source
        assert src.cache_bytes == 0

    def test_eviction_under_budget(self, pq_path):
        from spark_rapids_tpu.io.filecache import FileCache
        c = FileCache(max_bytes=100)
        t = pa.table({"x": pa.array(np.zeros(1000))})  # 8KB > budget
        c.put(("k",), [t])
        assert c.get(("k",)) is None  # too big to cache

    def test_mtime_invalidation(self, tmp_path):
        path = str(tmp_path / "f.parquet")
        pq.write_table(pa.table({"x": pa.array([1, 2, 3])}), path)
        src = ParquetSource(path, cache_bytes=1 << 30)
        from spark_rapids_tpu.io import filecache
        filecache.clear_file_cache()
        assert sum(t.num_rows for t in src()) == 3
        pq.write_table(pa.table({"x": pa.array([1, 2, 3, 4])}), path)
        os.utime(path, (0, 0))  # force mtime change
        assert sum(t.num_rows for t in src()) == 4


class TestPrefetch:
    def test_prefetch_yields_all_batches(self, pq_path):
        src = ParquetSource(pq_path, batch_rows=1000, num_threads=4)
        total = sum(t.num_rows for t in src())
        assert total == 10_000

    def test_prefetch_propagates_errors(self, tmp_path):
        path = str(tmp_path / "bad.parquet")
        with open(path, "wb") as f:
            f.write(b"not parquet")
        with pytest.raises(Exception):
            src = ParquetSource(path, num_threads=4)
            list(src())

    def test_no_prefetch_mode(self, pq_path):
        src = ParquetSource(pq_path, batch_rows=1000, num_threads=0)
        assert sum(t.num_rows for t in src()) == 10_000

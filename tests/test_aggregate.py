"""Aggregation tests (hash_aggregate_test.py analog)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from .support import (DoubleGen, IntGen, LongGen, StringGen,
                      assert_rows_equal, gen_table, pdf_rows)


def F():
    from spark_rapids_tpu.sql import functions
    return functions


@pytest.fixture(scope="module")
def agg_df(session, rng):
    table, pdf = gen_table(rng, {
        "k": IntGen(lo=0, hi=10),
        "k2": IntGen(lo=0, hi=3, nullable=False),
        "v": IntGen(lo=-100, hi=100),
        "d": DoubleGen(special=False),
    }, 400)
    return session.create_dataframe(table), pdf


def _oracle_grouped(pdf, keys):
    g = pdf.groupby(keys, dropna=False)
    exp = g.agg(s=("v", lambda x: x.sum(min_count=1)),
                c=("v", "count"),
                mn=("v", "min"),
                mx=("v", "max"),
                av=("d", "mean"),
                n=("v", "size")).reset_index()
    return exp


def test_grouped_aggs_single_key(agg_df):
    df, pdf = agg_df
    f = F()
    out = df.group_by("k").agg(
        f.sum(f.col("v")).alias("s"),
        f.count(f.col("v")).alias("c"),
        f.min(f.col("v")).alias("mn"),
        f.max(f.col("v")).alias("mx"),
        f.avg(f.col("d")).alias("av"),
        f.count_star().alias("n"),
    ).collect()
    exp = _oracle_grouped(pdf, ["k"])
    assert_rows_equal(out, pdf_rows(exp), approx_float=True)


def test_grouped_aggs_multi_key(agg_df):
    df, pdf = agg_df
    f = F()
    out = df.group_by("k", "k2").agg(f.sum(f.col("v")).alias("s")).collect()
    exp = pdf.groupby(["k", "k2"], dropna=False).agg(
        s=("v", lambda x: x.sum(min_count=1))).reset_index()
    assert_rows_equal(out, pdf_rows(exp))


def test_ungrouped_aggs(agg_df):
    df, pdf = agg_df
    f = F()
    out = df.agg(f.sum(f.col("v")).alias("s"),
                 f.count(f.col("v")).alias("c"),
                 f.min(f.col("v")).alias("mn"),
                 f.max(f.col("v")).alias("mx"),
                 f.count_star().alias("n")).collect()
    assert out == [(int(pdf.v.sum()), int(pdf.v.count()),
                    int(pdf.v.min()), int(pdf.v.max()), len(pdf))]


def test_sum_all_null_group_is_null(session):
    f = F()
    df = session.create_dataframe(
        {"k": [1, 1, 2], "v": pd.array([None, None, 5], dtype="Int64")})
    out = sorted(df.group_by("k").agg(f.sum(f.col("v")).alias("s")).collect())
    assert out == [(1, None), (2, 5)]


def test_count_empty(session):
    f = F()
    df = session.create_dataframe({"a": [1, 2, 3]}).where(f.col("a") > 99)
    assert df.count() == 0
    out = df.agg(f.sum(f.col("a")).alias("s")).collect()
    assert out == [(None,)]


def test_avg_int_is_double(session):
    f = F()
    df = session.create_dataframe({"a": [1, 2], "k": [0, 0]})
    out = df.group_by("k").agg(f.avg(f.col("a")).alias("m")).collect()
    assert out == [(0, 1.5)]


def test_distinct_numeric(session):
    df = session.create_dataframe({"a": [1, 2, 2, 3, 3, 3]})
    assert sorted(r[0] for r in df.distinct().collect()) == [1, 2, 3]


def test_grouped_string_key_fallback(session, rng):
    f = F()
    table, pdf = gen_table(rng, {"s": StringGen(max_len=3, null_prob=0.2),
                                 "v": IntGen(nullable=False, lo=0, hi=50)}, 200)
    df = session.create_dataframe(table)
    out = df.group_by("s").agg(f.sum(f.col("v")).alias("sv")).collect()
    exp = pdf.groupby("s", dropna=False).agg(sv=("v", "sum")).reset_index()
    assert_rows_equal(out, pdf_rows(exp))


def test_float_key_nan_groups_merge(session):
    f = F()
    nan = float("nan")
    df = session.create_dataframe({"k": [nan, nan, 1.0, -0.0, 0.0],
                                   "v": [1, 2, 3, 4, 5]})
    out = df.group_by("k").agg(f.sum(f.col("v")).alias("s")).collect()
    by_key = {}
    for k, s in out:
        key = "nan" if (k is not None and np.isnan(k)) else k
        by_key[key] = s
    assert by_key["nan"] == 3      # NaN normalized to one group
    assert by_key[0.0] == 9        # -0.0 and 0.0 merge


class TestStatisticalAggregates:
    """stddev/variance/corr/covar/percentile vs pandas (AggregateFunctions
    .scala stat-agg family)."""

    @pytest.fixture(scope="class")
    def stat_df(self, session, rng):
        from .support import DoubleGen, IntGen, gen_table
        table, pdf = gen_table(rng, {
            "g": IntGen(lo=0, hi=4, dtype="int32", nullable=False),
            "x": DoubleGen(special=False, nullable=False),
            "y": DoubleGen(special=False, nullable=False),
        }, 400)
        return session.create_dataframe(table), pdf

    def test_grouped_stddev_variance(self, stat_df):
        f = F()
        df, pdf = stat_df
        out = df.group_by("g").agg(
            f.stddev(f.col("x")).alias("ss"),
            f.stddev_pop(f.col("x")).alias("sp"),
            f.variance(f.col("x")).alias("vs"),
            f.var_pop(f.col("x")).alias("vp"))
        plan = out.explain_string()
        assert not any(ln.strip().startswith("!")
                       for ln in plan.splitlines()[2:]), plan
        got = {r[0]: r[1:] for r in out.collect()}
        g = pdf.groupby("g")["x"]
        for k in g.groups:
            ss, sp, vs, vp = got[k]
            import math
            for got_v, exp_v in [(ss, g.get_group(k).std(ddof=1)),
                                 (sp, g.get_group(k).std(ddof=0)),
                                 (vs, g.get_group(k).var(ddof=1)),
                                 (vp, g.get_group(k).var(ddof=0))]:
                assert math.isclose(got_v, exp_v, rel_tol=1e-9), (k, got_v,
                                                                 exp_v)

    def test_ungrouped_corr_covar(self, stat_df):
        f = F()
        df, pdf = stat_df
        got = df.agg(f.corr("x", "y").alias("c"),
                     f.covar_pop("x", "y").alias("cp"),
                     f.covar_samp("x", "y").alias("cs")).collect()[0]
        exp_c = pdf["x"].corr(pdf["y"])
        exp_cs = pdf["x"].cov(pdf["y"])
        n = len(pdf)
        exp_cp = exp_cs * (n - 1) / n
        assert abs(got[0] - exp_c) < 1e-9
        import math
        assert math.isclose(got[1], exp_cp, rel_tol=1e-9)
        assert math.isclose(got[2], exp_cs, rel_tol=1e-9)

    def test_stddev_single_row_is_null(self, session):
        f = F()
        import math
        t = pa.table({"g": pa.array([1, 1, 2], type=pa.int64()),
                      "x": pa.array([1.0, 3.0, 5.0])})
        df = session.create_dataframe(t)
        got = dict(df.group_by("g").agg(
            f.stddev(f.col("x")).alias("s")).collect())
        assert abs(got[1] - math.sqrt(2.0)) < 1e-12
        # n==1 → NULL (Spark 3.1+ default, legacy.statisticalAggregate off)
        assert got[2] is None

    def test_percentile_cpu_fallback(self, session, rng):
        f = F()
        import numpy as np
        vals = rng.random(101).tolist()
        df = session.create_dataframe(pa.table({"x": vals}))
        out = df.agg(f.percentile(f.col("x"), 0.5).alias("p"))
        plan = out.explain_string()
        assert "CPU only" in plan  # tagged fallback, not a crash
        got = out.collect()[0][0]
        assert abs(got - float(np.percentile(vals, 50.0))) < 1e-12

    def test_corr_with_nulls_pairwise(self, session):
        f = F()
        t = pa.table({
            "x": pa.array([1.0, 2.0, None, 4.0, 5.0]),
            "y": pa.array([2.0, None, 3.0, 8.0, 10.0]),
        })
        df = session.create_dataframe(t)
        got = df.agg(f.corr("x", "y").alias("c")).collect()[0][0]
        import pandas as pd
        pdf = pd.DataFrame({"x": [1.0, 4.0, 5.0], "y": [2.0, 8.0, 10.0]})
        assert abs(got - pdf["x"].corr(pdf["y"])) < 1e-12


class TestCompoundAggExpressions:
    """agg() with expressions OVER aggregate results (Spark's physical
    aggregate + resultExpressions split): sum(v)*0.2, max-min, ratios."""

    def test_scaled_and_ratio(self, session):
        f = F()
        df = session.create_dataframe({"k": [1, 1, 2], "v": [1.0, 3.0, 10.0]})
        got = sorted(df.group_by("k").agg(
            (f.avg(f.col("v")) * 0.2).alias("lim"),
            f.sum(f.col("v")).alias("s"),
            (f.sum(f.col("v")) / f.count_star()).alias("manual_avg"))
            .collect())
        assert got == [(1, pytest.approx(0.4), 4.0, 2.0),
                       (2, pytest.approx(2.0), 10.0, 10.0)]

    def test_ungrouped_compound(self, session):
        f = F()
        df = session.create_dataframe({"v": [7.0, 7.0]})
        assert df.agg((f.sum(f.col("v")) / 7.0).alias("w")).collect() \
            == [(2.0,)]

    def test_spread(self, session):
        f = F()
        df = session.create_dataframe({"k": [1, 1, 2], "v": [1.0, 3.0, 10.0]})
        got = df.group_by("k").agg(
            (f.max(f.col("v")) - f.min(f.col("v"))).alias("spread")) \
            .sort("k").collect()
        assert got == [(1, 2.0), (2, 0.0)]

    def test_no_aggregate_rejected(self, session):
        f = F()
        df = session.create_dataframe({"k": [1], "v": [1.0]})
        with pytest.raises(ValueError, match="aggregate function"):
            df.group_by("k").agg((f.col("v") * 2).alias("x"))

    def test_duplicate_aggs_planned_once(self, session):
        f = F()
        from spark_rapids_tpu.plan.overrides import apply_overrides
        df = session.create_dataframe({"k": [1, 1], "v": [1.0, 2.0]})
        q = df.group_by("k").agg(
            f.sum(f.col("v")).alias("s"),
            (f.sum(f.col("v")) / f.count_star()).alias("m"))
        agg = q._plan.children[0]
        assert len(agg.agg_exprs) == 2  # sum deduped, count separate
        got = q.collect()
        assert got == [(1, 3.0, 1.5)]

    def test_stray_row_column_is_analysis_error(self, session):
        f = F()
        df = session.create_dataframe({"k": [1], "v": [1.0]})
        with pytest.raises(ValueError, match="non-grouping"):
            df.group_by("k").agg(
                f.sum(f.col("v")).alias("s"),
                (f.col("v") + f.sum(f.col("v"))).alias("bad"))


class TestAggRepartitionFallback:
    """aggregate.scala:711 GpuMergeAggregateIterator analog: merged
    output that outgrows batchSizeRows re-partitions by key hash into
    bounded buckets (final/complete) or emits early (partial)."""

    def _data(self, rng, n=20_000, groups=5_000):
        import pyarrow as pa
        # sparse keys (stride 2^40) defeat the dense direct-address agg
        # so these tests exercise the sort + re-partition fallback path
        return pa.table({
            "k": pa.array((rng.integers(0, groups, n) << 40).astype(
                np.int64)),
            "k2": pa.array((rng.integers(0, groups, n) * 7).astype(
                np.int64)),
            "v": pa.array(rng.uniform(0, 10, n)),
        })

    def test_complete_mode_bucketed(self, fresh_session, rng):
        from spark_rapids_tpu.sql import functions as F
        sess = fresh_session
        t = self._data(rng)
        pdf = t.to_pandas()
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 2048)
        sess.conf.set("spark.rapids.tpu.sql.batchSizeBytes", 2048 * 32)
        df = (sess.create_dataframe(t).group_by("k")
              .agg(F.sum(F.col("v")).alias("s"),
                   F.count_star().alias("c")))
        # pin the code path: the re-partition fallback must actually fire
        from spark_rapids_tpu.plan.physical import CollectExec, ExecContext
        phys = sess._plan_physical(df._plan)
        ctx = ExecContext(sess._tpu_conf(), device=sess.device)
        tbl = CollectExec(phys).collect_arrow(ctx)
        assert sum(ms.values.get("aggRepartitions", 0)
                   for ms in ctx.metrics.values()) >= 1
        got = dict((r[0], (r[1], r[2]))
                   for r in zip(*[c.to_pylist() for c in tbl.columns]))
        want = pdf.groupby("k").agg(s=("v", "sum"), c=("v", "size"))
        assert len(got) == len(want)
        for k, row in want.iterrows():
            s, c = got[int(k)]
            assert abs(s - row.s) < 1e-9 * max(1.0, abs(row.s))
            assert c == row.c

    def test_two_phase_partial_emits_early(self, fresh_session, rng):
        from spark_rapids_tpu.sql import functions as F
        sess = fresh_session
        t = self._data(rng)
        pdf = t.to_pandas()
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 2048)
        sess.conf.set("spark.rapids.tpu.sql.batchSizeBytes", 2048 * 32)
        sess.conf.set(
            "spark.rapids.tpu.sql.agg.singleProcessComplete", False)
        sess.conf.set("spark.rapids.tpu.sql.agg.skipPartialAggRatio", 1.0)
        df = (sess.create_dataframe(t).group_by("k")
              .agg(F.min(F.col("v")).alias("mn")))
        got = dict(df.collect())
        want = pdf.groupby("k")["v"].min()
        assert len(got) == len(want)
        for k, v in want.items():
            assert abs(got[int(k)] - v) < 1e-12

    def test_multi_key_bucketed(self, fresh_session, rng):
        from spark_rapids_tpu.sql import functions as F
        sess = fresh_session
        t = self._data(rng)
        pdf = t.to_pandas()
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1024)
        sess.conf.set("spark.rapids.tpu.sql.batchSizeBytes", 1024 * 32)
        df = (sess.create_dataframe(t).group_by("k", "k2")
              .agg(F.sum(F.col("v")).alias("s")))
        got = df.collect()
        want = pdf.groupby(["k", "k2"])["v"].sum()
        assert len(got) == len(want)


class TestDenseResidualAgg:
    """Multi-key dense aggregation: a bounded int primary key scatters
    into domain accumulators while residual keys (functionally dependent
    attributes, the q3/q10/q18 shape) prove per-slot consistency via
    scatter-min/max channels; any violation replays through the sort
    path.  Both arms verified against pandas."""

    def _run(self, sess, t, keys, want_metric):
        from spark_rapids_tpu.plan.physical import CollectExec, ExecContext
        from spark_rapids_tpu.sql import functions as F
        df = (sess.create_dataframe(t).group_by(*keys)
              .agg(F.sum(F.col("v")).alias("s")))
        phys = sess._plan_physical(df._plan)
        ctx = ExecContext(sess._tpu_conf(), device=sess.device)
        tbl = CollectExec(phys).collect_arrow(ctx)
        got_metric = sum(ms.values.get(want_metric, 0)
                         for ms in ctx.metrics.values())
        assert got_metric >= 1, \
            f"expected {want_metric} to fire; metrics={ctx.metrics}"
        return tbl.to_pandas()

    def test_dependent_residuals_dense(self, fresh_session, rng):
        import pyarrow as pa
        sess = fresh_session
        n, groups = 50_000, 4_000
        k = rng.integers(0, groups, n).astype(np.int64)
        name = np.array([f"name#{i % 97}" for i in range(groups)])
        bal = (np.arange(groups) * 1.25).astype(np.float64)
        t = pa.table({"k": k, "name": name[k], "bal": bal[k],
                      "v": rng.uniform(0, 10, n)})
        out = self._run(sess, t, ["k", "name", "bal"], "aggDensePath")
        want = (t.to_pandas().groupby(["k", "name", "bal"])
                .agg(s=("v", "sum")).reset_index())
        got = out.sort_values("k").reset_index(drop=True)
        want = want.sort_values("k").reset_index(drop=True)
        assert len(got) == len(want)
        assert (got["k"].to_numpy() == want["k"].to_numpy()).all()
        assert list(got["name"]) == list(want["name"])
        np.testing.assert_allclose(got["bal"], want["bal"])
        np.testing.assert_allclose(got["s"], want["s"], rtol=1e-9)

    def test_independent_residuals_rejected_upfront(self, fresh_session,
                                                     rng):
        """Clearly-independent residuals never enter the dense path at
        all: the sampled distinct-count probe rejects them in the same
        stats fetch (q21's DISTINCT was the replay victim)."""
        import pyarrow as pa
        from spark_rapids_tpu.plan.physical import (CollectExec,
                                                    ExecContext)
        from spark_rapids_tpu.sql import functions as F
        sess = fresh_session
        n, groups = 20_000, 500
        k = rng.integers(0, groups, n).astype(np.int64)
        r2 = rng.integers(0, 50, n).astype(np.int64)
        t = pa.table({"k": k, "r2": r2, "v": rng.uniform(0, 10, n)})
        df = (sess.create_dataframe(t).group_by("k", "r2")
              .agg(F.sum(F.col("v")).alias("s")))
        phys = sess._plan_physical(df._plan)
        ctx = ExecContext(sess._tpu_conf(), device=sess.device)
        tbl = CollectExec(phys).collect_arrow(ctx)
        for ms in ctx.metrics.values():
            assert ms.values.get("aggDensePath", 0) == 0
            assert ms.values.get("aggDenseResidualFallback", 0) == 0
        want = (t.to_pandas().groupby(["k", "r2"])
                .agg(s=("v", "sum")).reset_index())
        assert tbl.num_rows == len(want)

    def test_violated_residuals_fall_back(self, fresh_session, rng):
        import pyarrow as pa
        sess = fresh_session
        # dependent within the 2^18-row sample prefix, violated after:
        # the upfront probe passes, the end-of-stream consistency check
        # catches it, and the buffered input replays through the sort
        # path with exact results
        n, groups = 300_000, 500
        k = rng.integers(0, groups, n).astype(np.int64)
        r2 = (k * 3).astype(np.int64)
        r2[(1 << 18) + 100:] = rng.integers(
            10_000, 10_050, n - (1 << 18) - 100)
        t = pa.table({"k": k, "r2": r2, "v": rng.uniform(0, 10, n)})
        out = self._run(sess, t, ["k", "r2"],
                        "aggDenseResidualFallback")
        want = (t.to_pandas().groupby(["k", "r2"])
                .agg(s=("v", "sum")).reset_index())
        got = out.sort_values(["k", "r2"]).reset_index(drop=True)
        want = want.sort_values(["k", "r2"]).reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got["s"], want["s"], rtol=1e-9)

    def test_null_residuals_consistent(self, fresh_session, rng):
        import pyarrow as pa
        sess = fresh_session
        n, groups = 10_000, 300
        k = rng.integers(0, groups, n).astype(np.int64)
        # dependent residual where some groups are entirely NULL
        rvals = np.array([None if i % 5 == 0 else i * 3
                          for i in range(groups)], dtype=object)
        t = pa.table({"k": k,
                      "r": pa.array([rvals[i] for i in k],
                                    type=pa.int64()),
                      "v": rng.uniform(0, 10, n)})
        out = self._run(sess, t, ["k", "r"], "aggDensePath")
        want = (t.to_pandas().groupby(["k", "r"], dropna=False)
                .agg(s=("v", "sum")).reset_index())
        assert len(out) == len(want)
        got = out.sort_values("k").reset_index(drop=True)
        want = want.sort_values("k").reset_index(drop=True)
        np.testing.assert_allclose(got["s"], want["s"], rtol=1e-9)
        gr = got["r"].to_numpy(dtype=object)
        wr = want["r"].to_numpy(dtype=object)
        for a, b in zip(gr, wr):
            assert (a is None or (isinstance(a, float) and np.isnan(a))) \
                == (b is None or (isinstance(b, float) and np.isnan(b))), \
                (a, b)


class TestCountDistinct:
    """count(DISTINCT ...) lowering (RewriteDistinctAggregates analog):
    dedup aggregation + count per distinct set joined back to the plain
    aggregates on the group keys; groupless via a constant key."""

    def _t(self, rng, n=2000):
        import pyarrow as pa
        return pa.table({
            "k": rng.integers(0, 7, n),
            "v": rng.integers(0, 40, n),
            "w": rng.uniform(0, 1, n),
            "s": pa.array([None if i % 5 == 0 else f"s{i % 13}"
                           for i in range(n)]),
        })

    def test_grouped_mixed(self, fresh_session, rng):
        from spark_rapids_tpu.sql import functions as F
        t = self._t(rng)
        df = fresh_session.create_dataframe(t)
        got = sorted(df.group_by("k").agg(
            F.count_distinct(F.col("v")).alias("dv"),
            F.sum(F.col("w")).alias("sw"),
            F.count_distinct(F.col("s")).alias("ds")).collect())
        pd_ = t.to_pandas()
        want = sorted((int(k), g.v.nunique(), g.w.sum(), g.s.nunique())
                      for k, g in pd_.groupby("k"))
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[0] == w[0] and g[1] == w[1] and g[3] == w[3]
            assert abs(g[2] - w[2]) < 1e-9

    def test_groupless_and_multicol(self, fresh_session, rng):
        from spark_rapids_tpu.sql import functions as F
        t = self._t(rng)
        df = fresh_session.create_dataframe(t)
        pd_ = t.to_pandas()
        (d,), = df.agg(F.count_distinct(F.col("v")).alias("d")).collect()
        assert d == pd_.v.nunique()
        (d2, s2), = df.agg(
            F.count_distinct(F.col("v"), F.col("k")).alias("d"),
            F.sum(F.col("w")).alias("s")).collect()
        assert d2 == len(pd_.groupby(["v", "k"]))
        assert abs(s2 - pd_.w.sum()) < 1e-9

    def test_nulls_not_counted(self, fresh_session):
        import pyarrow as pa
        from spark_rapids_tpu.sql import functions as F
        t = pa.table({"k": [1, 1, 1, 2],
                      "s": pa.array(["a", None, "a", None])})
        df = fresh_session.create_dataframe(t)
        got = sorted(df.group_by("k").agg(
            F.count_distinct(F.col("s")).alias("d")).collect())
        assert got == [(1, 1), (2, 0)]

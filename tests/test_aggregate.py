"""Aggregation tests (hash_aggregate_test.py analog)."""

import numpy as np
import pandas as pd
import pytest

from .support import (DoubleGen, IntGen, LongGen, StringGen,
                      assert_rows_equal, gen_table, pdf_rows)


def F():
    from spark_rapids_tpu.sql import functions
    return functions


@pytest.fixture(scope="module")
def agg_df(session, rng):
    table, pdf = gen_table(rng, {
        "k": IntGen(lo=0, hi=10),
        "k2": IntGen(lo=0, hi=3, nullable=False),
        "v": IntGen(lo=-100, hi=100),
        "d": DoubleGen(special=False),
    }, 400)
    return session.create_dataframe(table), pdf


def _oracle_grouped(pdf, keys):
    g = pdf.groupby(keys, dropna=False)
    exp = g.agg(s=("v", lambda x: x.sum(min_count=1)),
                c=("v", "count"),
                mn=("v", "min"),
                mx=("v", "max"),
                av=("d", "mean"),
                n=("v", "size")).reset_index()
    return exp


def test_grouped_aggs_single_key(agg_df):
    df, pdf = agg_df
    f = F()
    out = df.group_by("k").agg(
        f.sum(f.col("v")).alias("s"),
        f.count(f.col("v")).alias("c"),
        f.min(f.col("v")).alias("mn"),
        f.max(f.col("v")).alias("mx"),
        f.avg(f.col("d")).alias("av"),
        f.count_star().alias("n"),
    ).collect()
    exp = _oracle_grouped(pdf, ["k"])
    assert_rows_equal(out, pdf_rows(exp), approx_float=True)


def test_grouped_aggs_multi_key(agg_df):
    df, pdf = agg_df
    f = F()
    out = df.group_by("k", "k2").agg(f.sum(f.col("v")).alias("s")).collect()
    exp = pdf.groupby(["k", "k2"], dropna=False).agg(
        s=("v", lambda x: x.sum(min_count=1))).reset_index()
    assert_rows_equal(out, pdf_rows(exp))


def test_ungrouped_aggs(agg_df):
    df, pdf = agg_df
    f = F()
    out = df.agg(f.sum(f.col("v")).alias("s"),
                 f.count(f.col("v")).alias("c"),
                 f.min(f.col("v")).alias("mn"),
                 f.max(f.col("v")).alias("mx"),
                 f.count_star().alias("n")).collect()
    assert out == [(int(pdf.v.sum()), int(pdf.v.count()),
                    int(pdf.v.min()), int(pdf.v.max()), len(pdf))]


def test_sum_all_null_group_is_null(session):
    f = F()
    df = session.create_dataframe(
        {"k": [1, 1, 2], "v": pd.array([None, None, 5], dtype="Int64")})
    out = sorted(df.group_by("k").agg(f.sum(f.col("v")).alias("s")).collect())
    assert out == [(1, None), (2, 5)]


def test_count_empty(session):
    f = F()
    df = session.create_dataframe({"a": [1, 2, 3]}).where(f.col("a") > 99)
    assert df.count() == 0
    out = df.agg(f.sum(f.col("a")).alias("s")).collect()
    assert out == [(None,)]


def test_avg_int_is_double(session):
    f = F()
    df = session.create_dataframe({"a": [1, 2], "k": [0, 0]})
    out = df.group_by("k").agg(f.avg(f.col("a")).alias("m")).collect()
    assert out == [(0, 1.5)]


def test_distinct_numeric(session):
    df = session.create_dataframe({"a": [1, 2, 2, 3, 3, 3]})
    assert sorted(r[0] for r in df.distinct().collect()) == [1, 2, 3]


def test_grouped_string_key_fallback(session, rng):
    f = F()
    table, pdf = gen_table(rng, {"s": StringGen(max_len=3, null_prob=0.2),
                                 "v": IntGen(nullable=False, lo=0, hi=50)}, 200)
    df = session.create_dataframe(table)
    out = df.group_by("s").agg(f.sum(f.col("v")).alias("sv")).collect()
    exp = pdf.groupby("s", dropna=False).agg(sv=("v", "sum")).reset_index()
    assert_rows_equal(out, pdf_rows(exp))


def test_float_key_nan_groups_merge(session):
    f = F()
    nan = float("nan")
    df = session.create_dataframe({"k": [nan, nan, 1.0, -0.0, 0.0],
                                   "v": [1, 2, 3, 4, 5]})
    out = df.group_by("k").agg(f.sum(f.col("v")).alias("s")).collect()
    by_key = {}
    for k, s in out:
        key = "nan" if (k is not None and np.isnan(k)) else k
        by_key[key] = s
    assert by_key["nan"] == 3      # NaN normalized to one group
    assert by_key[0.0] == 9        # -0.0 and 0.0 merge

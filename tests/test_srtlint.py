"""tools/srtlint — the unified AST static analysis engine.

Covers, per pass: detection on fixture snippets (including the
defect classes the retired regex scanners provably missed), reasoned
suppression, and the baseline workflow; plus the engine surfaces
(CLI, JSON, explain, mtime-keyed cache) and the acceptance gates:
the real tree is clean and a full run fits the collection wall budget.
"""

import json
import os
import time

import pytest

from tools.srtlint import engine
from tools.srtlint.engine import run as lint_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    """Write {relpath: source} under a fixture spark_rapids_tpu/."""
    for rel, src in files.items():
        p = tmp_path / "spark_rapids_tpu" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path)


def _lint(tmp_path, files, rules):
    return lint_run(_tree(tmp_path, files),
                    roots=("spark_rapids_tpu",), rules=rules)


# ---------------------------------------------------------------------------
# ported passes: the regex scanners' false-negative classes are caught
# ---------------------------------------------------------------------------

class TestBlockingFetch:
    def test_aliased_device_get_regex_false_negative(self, tmp_path):
        """`from jax import device_get as dg` dodged the old
        `jax.device_get(` line regex entirely."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "from jax import device_get as dg\n"
            "def f(x):\n"
            "    return dg(x)\n")}, ["blocking-fetch"])
        assert [f.line for f in report.failing] == [3]
        assert "choke point" in report.failing[0].message

    def test_multiline_asarray_and_suppression(self, tmp_path):
        """A call spanning lines (regex saw only line 1) + a reasoned
        legacy marker anywhere on the statement suppresses."""
        report = _lint(tmp_path, {"ops/bad.py": (
            "import numpy as np\n"
            "def f(col):\n"
            "    return np.asarray(\n"
            "        col.data)\n"
            "def g(col):\n"
            "    return np.asarray(\n"
            "        col.codes)  # choke-point-ok (host column; no device buffer)\n")},
            ["blocking-fetch"])
        assert [f.line for f in report.failing] == [3]
        assert len(report.suppressed) == 1

    def test_outside_operator_layer_ignored(self, tmp_path):
        report = _lint(tmp_path, {"io/x.py": (
            "import jax\n"
            "def f(x):\n"
            "    return jax.device_get(x)\n")}, ["blocking-fetch"])
        assert report.failing == []


class TestSpanTiming:
    def test_aliased_clock_import(self, tmp_path):
        """`from time import perf_counter` was invisible to the
        `time.perf_counter(` regex."""
        report = _lint(tmp_path, {"parallel/bad.py": (
            "from time import perf_counter as pc\n"
            "t0 = pc()\n")}, ["span-timing"])
        assert [f.line for f in report.failing] == [2]


class TestCtxThreads:
    def test_evidence_beyond_regex_window(self, tmp_path):
        """copy_context evidence 5+ lines from the creation site was a
        false POSITIVE for the ±3-line regex window; the AST pass
        scopes evidence to the enclosing function."""
        src = (
            "import contextvars, threading\n"
            "def spawn(fn):\n"
            "    cctx = contextvars.copy_context()\n"
            "    a = 1\n"
            "    b = 2\n"
            "    c = 3\n"
            "    d = 4\n"
            "    th = threading.Thread(target=lambda: cctx.run(fn))\n"
            "    th.start()\n")
        report = _lint(tmp_path, {"runtime/pool.py": src},
                       ["ctx-threads"])
        assert report.failing == []

    def test_detect_and_reasoned_suppress(self, tmp_path):
        report = _lint(tmp_path, {"runtime/bad.py": (
            "import threading\n"
            "def spawn(fn):\n"
            "    threading.Thread(target=fn).start()\n"
            "def ok(fn):\n"
            "    threading.Thread(target=fn).start()  # ctx-ok (process-lifetime control plane)\n")},
            ["ctx-threads"])
        assert [f.line for f in report.failing] == [3]
        assert len(report.suppressed) == 1


class TestCacheKeys:
    def test_aliased_constructor_and_multiline_literal(self, tmp_path):
        """Both regex false-negative classes: an aliased CacheKey
        import and a literal key split across lines."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "from ..cache.keys import CacheKey as CK\n"
            "def f(cache, schema):\n"
            "    k = CK('scan', (), None, None)\n"
            "    return cache.lookup_scan(\n"
            "        ('adhoc',\n"
            "         'tuple'), schema)\n")}, ["cache-keys"])
        assert sorted(f.line for f in report.failing) == [3, 4]

    def test_keys_module_itself_exempt(self, tmp_path):
        report = _lint(tmp_path, {"cache/keys.py": (
            "class CacheKey:\n"
            "    pass\n"
            "def scan_key():\n"
            "    return CacheKey()\n")}, ["cache-keys"])
        assert report.failing == []


class TestFaultPaths:
    def test_multiline_except_sleep_pair(self, tmp_path):
        """A sleep 10 lines into the handler suite: past the regex
        scanner's 8-line window, inside the AST handler scope."""
        filler = "".join(f"        x{i} = {i}\n" for i in range(10))
        report = _lint(tmp_path, {"io/bad.py": (
            "import time\n"
            "def r():\n"
            "    try:\n"
            "        return g()\n"
            "    except OSError:\n"
            + filler +
            "        time.sleep(0.1)\n")}, ["fault-paths"])
        assert len(report.failing) == 1
        assert "ad-hoc retry" in report.failing[0].message
        assert report.failing[0].line == 16

    def test_swallowed_fault_marker_on_pass_line(self, tmp_path):
        report = _lint(tmp_path, {"io/x.py": (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass  # fault-ok (best-effort hint)\n"
            "    try:\n"
            "        g()\n"
            "    except BaseException:\n"
            "        pass\n")}, ["fault-paths"])
        assert [f.line for f in report.failing] == [8]
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# new passes
# ---------------------------------------------------------------------------

class TestReleasePaths:
    def test_leaked_handle_detected(self, tmp_path):
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    h.get()\n")}, ["release-paths"])
        assert len(report.failing) == 1
        assert "never released" in report.failing[0].message

    def test_straight_line_release_flagged(self, tmp_path):
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    work(h)\n"
            "    h.close()\n")}, ["release-paths"])
        assert len(report.failing) == 1
        assert "straight-line" in report.failing[0].message

    def test_finally_release_clean(self, tmp_path):
        report = _lint(tmp_path, {"plan/ok.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    try:\n"
            "        work(h)\n"
            "    finally:\n"
            "        h.close()\n")}, ["release-paths"])
        assert report.failing == []

    def test_exit_edge_between_acquire_and_finally(self, tmp_path):
        """CFG-lite: a return between acquisition and its protecting
        try/finally is a leak edge."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(catalog, b, flag):\n"
            "    h = catalog.register(b)\n"
            "    if flag:\n"
            "        return None\n"
            "    try:\n"
            "        return work(h)\n"
            "    finally:\n"
            "        h.close()\n")}, ["release-paths"])
        assert len(report.failing) == 1
        assert report.failing[0].line == 4
        assert "leaks" in report.failing[0].message

    def test_escape_and_with_are_clean(self, tmp_path):
        report = _lint(tmp_path, {"plan/ok.py": (
            "def f(catalog, b, out):\n"
            "    h = catalog.register(b)\n"
            "    out.append(h)\n"
            "def g(sem):\n"
            "    with sem.acquire():\n"
            "        pass\n"
            "def r(cache, key):\n"
            "    hit = cache.lookup_broadcast(key)\n"
            "    return hit\n")}, ["release-paths"])
        assert report.failing == []

    def test_paired_void_quota(self, tmp_path):
        report = _lint(tmp_path, {"server/bad.py": (
            "def f(quotas, tenant):\n"
            "    quotas.acquire(tenant)\n"
            "    work()\n"
            "    quotas.release(tenant)\n"
            "def ok(quotas, tenant):\n"
            "    quotas.acquire(tenant)\n"
            "    try:\n"
            "        work()\n"
            "    finally:\n"
            "        quotas.release(tenant)\n")}, ["release-paths"])
        assert [f.line for f in report.failing] == [2]
        assert "finally" in report.failing[0].message


class TestLockDiscipline:
    def test_blocking_under_lock(self, tmp_path):
        report = _lint(tmp_path, {"service/bad.py": (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self, sock):\n"
            "        with self._lock:\n"
            "            sock.recv(4096)\n")}, ["lock-discipline"])
        assert len(report.failing) == 1
        assert "sock.recv" in report.failing[0].message

    def test_cv_self_wait_not_flagged(self, tmp_path):
        report = _lint(tmp_path, {"service/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "    def f(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait()\n")}, ["lock-discipline"])
        assert report.failing == []

    def test_blocking_through_helper(self, tmp_path):
        """Interprocedural summary: the blocking call hides one level
        down in a same-module helper."""
        report = _lint(tmp_path, {"service/bad.py": (
            "import threading\n"
            "def _pull(sock):\n"
            "    return sock.recv(4096)\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self, sock):\n"
            "        with self._lock:\n"
            "            return _pull(sock)\n")}, ["lock-discipline"])
        assert len(report.failing) == 1
        assert "reaches blocking" in report.failing[0].message

    def test_lock_order_cycle(self, tmp_path):
        report = _lint(tmp_path, {"cache/bad.py": (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def ba(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n")}, ["lock-discipline"])
        cyc = [f for f in report.failing if "cycle" in f.message]
        assert len(cyc) == 2  # one per participating edge
        assert "one global order" in cyc[0].message

    def test_consistent_order_no_cycle(self, tmp_path):
        report = _lint(tmp_path, {"cache/ok.py": (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def ab2(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n")}, ["lock-discipline"])
        assert report.failing == []


_CONF_FIXTURE = {
    "config.py": (
        "def register(key, default, doc, **kw):\n"
        "    return key\n"
        "A = register('spark.rapids.tpu.a', 1, 'used and documented')\n"
        "B = register('spark.rapids.tpu.b', 1, 'internal',\n"
        "             internal=True)\n"
        "ORPHAN = register('spark.rapids.tpu.orphan', 1, 'dead')\n"),
    "user.py": (
        "from .config import B\n"
        "def f(conf, tier):\n"
        "    x = conf['spark.rapids.tpu.a']\n"
        "    y = conf['spark.rapids.tpu.nope']\n"
        "    z = conf[f'spark.rapids.tpu.{tier}.enabled']\n"
        "    return x, y, z, B\n"),
}


class TestConfRegistry:
    def _run(self, tmp_path, docs: str):
        root = _tree(tmp_path, _CONF_FIXTURE)
        os.makedirs(os.path.join(root, "docs"), exist_ok=True)
        with open(os.path.join(root, "docs", "configs.md"), "w") as f:
            f.write(docs)
        return lint_run(root, roots=("spark_rapids_tpu",),
                        rules=["conf-registry"])

    def test_unknown_dynamic_orphan_and_docs(self, tmp_path):
        report = self._run(
            tmp_path,
            "| spark.rapids.tpu.a | 1 | doc |\n"
            "| spark.rapids.tpu.orphan | 1 | doc |\n"
            "| spark.rapids.tpu.stale | 1 | doc |\n")
        msgs = sorted(f.message for f in report.failing)
        assert any("'spark.rapids.tpu.nope' is not registered" in m
                   for m in msgs)
        assert any("f-string" in m for m in msgs)
        assert any("'spark.rapids.tpu.orphan' is orphaned" in m
                   for m in msgs)
        assert any("no longer registered" in m for m in msgs)
        # the internal key B needs no docs entry and is referenced
        assert not any("'spark.rapids.tpu.b'" in m for m in msgs)

    def test_missing_doc_entry(self, tmp_path):
        report = self._run(tmp_path,
                           "| spark.rapids.tpu.orphan | 1 | doc |\n")
        assert any("missing from docs/configs.md" in f.message
                   and "'spark.rapids.tpu.a'" in f.message
                   for f in report.failing)


# ---------------------------------------------------------------------------
# engine: suppression hygiene, baseline workflow, cache, CLI
# ---------------------------------------------------------------------------

class TestEngine:
    def test_srtlint_ignore_syntax_and_reason_required(self, tmp_path):
        report = _lint(tmp_path, {"plan/x.py": (
            "import jax\n"
            "a = jax.device_get(1)  # srtlint: ignore[blocking-fetch] (test seed, not a device value)\n"
            "b = jax.device_get(2)  # srtlint: ignore[blocking-fetch]\n")},
            ["blocking-fetch"])
        assert [f.line for f in report.failing] == [3]
        assert "no reason" in report.failing[0].message
        assert [f.line for f in report.suppressed] == [2]
        assert "test seed" in report.suppressed[0].suppress_reason

    def test_baseline_workflow(self, tmp_path):
        files = {"plan/bad.py": ("import jax\n"
                                 "a = jax.device_get(1)\n")}
        root = _tree(tmp_path, files)
        bl = str(tmp_path / "baseline.json")
        report = lint_run(root, roots=("spark_rapids_tpu",),
                          rules=["blocking-fetch"], baseline_path=bl)
        assert len(report.failing) == 1
        engine.write_baseline(report.failing, bl)
        again = lint_run(root, roots=("spark_rapids_tpu",),
                         rules=["blocking-fetch"], baseline_path=bl)
        assert again.failing == []
        assert len(again.baselined) == 1
        # line drift does not invalidate the baseline entry
        files = {"plan/bad.py": ("import jax\n# pushed down\n"
                                 "a = jax.device_get(1)\n")}
        root = _tree(tmp_path, files)
        moved = lint_run(root, roots=("spark_rapids_tpu",),
                         rules=["blocking-fetch"], baseline_path=bl)
        assert moved.failing == []
        assert len(moved.baselined) == 1

    def test_cli_exit_codes_and_json(self, tmp_path, capsys):
        root = _tree(tmp_path, {"plan/bad.py": (
            "import jax\na = jax.device_get(1)\n")})
        assert engine.main(["--repo", root, "--json"]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["counts"]["failing"] == 1
        root2 = _tree(tmp_path / "clean", {"plan/ok.py": "x = 1\n"})
        assert engine.main(["--repo", root2]) == 0
        assert engine.main(["--explain", "lock-discipline"]) == 0
        assert "lock-acquisition graph" in capsys.readouterr().out
        assert engine.main(["--explain", "nope"]) == 2

    def test_explain_covers_all_nine_rules(self):
        rules = engine.available_rules()
        assert rules == ["blocking-fetch", "span-timing", "ctx-threads",
                         "cache-keys", "fault-paths", "release-paths",
                         "lock-discipline", "shutdown-paths",
                         "conf-registry"]
        for r in rules:
            assert r in engine.explain_rule(r)

    def test_parse_error_is_a_finding(self, tmp_path):
        report = _lint(tmp_path, {"plan/broken.py": "def f(:\n"},
                       ["blocking-fetch"])
        assert [f.rule for f in report.failing] == ["parse-error"]


class TestRealTree:
    def test_full_tree_clean_and_within_wall_budget(self):
        """Acceptance: all nine passes over the real tree, zero
        unsuppressed findings, every suppression reasoned, inside a
        collection-time wall budget."""
        t0 = time.perf_counter()
        report = engine.run(REPO)
        wall = time.perf_counter() - t0
        assert report.failing == [], \
            "\n".join(f"{f.path}:{f.line}: [{f.rule}] {f.message}"
                      for f in report.failing)
        assert report.files > 100
        assert all(f.suppress_reason for f in report.suppressed)
        assert set(report.pass_timings) == set(engine.available_rules())
        assert wall < 30.0, f"full scan took {wall:.1f}s"

    def test_conftest_entry_point_caches(self):
        """The mtime-keyed cache: a second call with an unchanged tree
        must come back from the memo in far under the five regex
        scanners' combined walk time."""
        from tools.srtlint import run_for_pytest
        first = run_for_pytest()
        t0 = time.perf_counter()
        second = run_for_pytest()
        cached_wall = time.perf_counter() - t0
        assert second.failing == first.failing == []
        assert cached_wall < 1.0

    def test_registry_docs_in_sync(self):
        """conf-registry's docs cross-check holds on the real tree —
        docs/configs.md matches TpuConf.help() exactly."""
        from spark_rapids_tpu.config import TpuConf
        with open(os.path.join(REPO, "docs", "configs.md")) as f:
            doc = f.read()
        for line in TpuConf.help().splitlines():
            assert line in doc


class TestShutdownPaths:
    def test_unjoined_attr_thread_detected(self, tmp_path):
        report = _lint(tmp_path, {"service/bad.py": (
            "import threading\n"
            "class S:\n"
            "    def start(self):\n"
            "        self._th = threading.Thread(target=self._loop)\n"
            "        self._th.start()\n"
            "    def close(self):\n"
            "        pass\n")}, ["shutdown-paths"])
        assert [f.line for f in report.failing] == [4]
        assert "never joined" in report.failing[0].message

    def test_join_without_timeout_still_flagged(self, tmp_path):
        """An unbounded join hangs the shutdown a wedged thread was
        supposed to be bounded by."""
        report = _lint(tmp_path, {"server/bad.py": (
            "import threading\n"
            "class S:\n"
            "    def start(self):\n"
            "        self._th = threading.Thread(target=self._loop)\n"
            "        self._th.start()\n"
            "    def close(self):\n"
            "        self._th.join()\n")}, ["shutdown-paths"])
        assert [f.line for f in report.failing] == [4]

    def test_no_handle_escape_detected_and_suppressed(self, tmp_path):
        report = _lint(tmp_path, {"parallel/bad.py": (
            "import threading\n"
            "def fire(fn):\n"
            "    threading.Thread(target=fn).start()\n"
            "def ok(fn):\n"
            "    threading.Thread(target=fn).start()  # srtlint: ignore[shutdown-paths] (hedge loser; socket timeout bounds it)\n")},
            ["shutdown-paths"])
        assert [f.line for f in report.failing] == [3]
        assert "no handle escapes" in report.failing[0].message
        assert len(report.suppressed) == 1

    def test_container_append_joined_in_close_clean(self, tmp_path):
        report = _lint(tmp_path, {"parallel/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def start(self):\n"
            "        t = threading.Thread(target=self._loop)\n"
            "        t.start()\n"
            "        self._threads.append(t)\n"
            "    def close(self):\n"
            "        for t in self._threads:\n"
            "            t.join(timeout=2.0)\n")}, ["shutdown-paths"])
        assert report.failing == []

    def test_dict_store_and_aliased_values_loop_clean(self, tmp_path):
        """The endpoint idiom: store into a dict, join through
        ``list(self._conn_threads.values())`` — two levels of local
        aliasing between the container and the join."""
        report = _lint(tmp_path, {"server/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def accept(self, cid):\n"
            "        th = threading.Thread(target=self._conn)\n"
            "        self._conn_threads[cid] = th\n"
            "        th.start()\n"
            "    def close(self):\n"
            "        threads = list(self._conn_threads.values())\n"
            "        for th in threads:\n"
            "            th.join(timeout=2.0)\n")}, ["shutdown-paths"])
        assert report.failing == []

    def test_same_function_join_clean(self, tmp_path):
        report = _lint(tmp_path, {"parallel/scatter.py": (
            "import threading\n"
            "def fan_out(fns):\n"
            "    ts = []\n"
            "    for fn in fns:\n"
            "        t = threading.Thread(target=fn)\n"
            "        ts.append(t)\n"
            "        t.start()\n"
            "    for t in ts:\n"
            "        t.join(timeout=30)\n")}, ["shutdown-paths"])
        assert report.failing == []

    def test_outside_serving_layers_ignored(self, tmp_path):
        report = _lint(tmp_path, {"runtime/bg.py": (
            "import threading\n"
            "def fire(fn):\n"
            "    threading.Thread(target=fn).start()\n")},
            ["shutdown-paths"])
        assert report.failing == []

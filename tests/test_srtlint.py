"""tools/srtlint — the unified AST static analysis engine.

Covers, per pass: detection on fixture snippets (including the
defect classes the retired regex scanners provably missed), reasoned
suppression, and the baseline workflow; plus the engine surfaces
(CLI, JSON, explain, mtime-keyed cache) and the acceptance gates:
the real tree is clean and a full run fits the collection wall budget.
"""

import json
import os
import time

import pytest

from tools.srtlint import engine
from tools.srtlint.engine import run as lint_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    """Write {relpath: source} under a fixture spark_rapids_tpu/."""
    for rel, src in files.items():
        p = tmp_path / "spark_rapids_tpu" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path)


def _lint(tmp_path, files, rules):
    return lint_run(_tree(tmp_path, files),
                    roots=("spark_rapids_tpu",), rules=rules)


# ---------------------------------------------------------------------------
# ported passes: the regex scanners' false-negative classes are caught
# ---------------------------------------------------------------------------

class TestBlockingFetch:
    def test_aliased_device_get_regex_false_negative(self, tmp_path):
        """`from jax import device_get as dg` dodged the old
        `jax.device_get(` line regex entirely."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "from jax import device_get as dg\n"
            "def f(x):\n"
            "    return dg(x)\n")}, ["blocking-fetch"])
        assert [f.line for f in report.failing] == [3]
        assert "choke point" in report.failing[0].message

    def test_multiline_asarray_and_suppression(self, tmp_path):
        """A call spanning lines (regex saw only line 1) + a reasoned
        legacy marker anywhere on the statement suppresses."""
        report = _lint(tmp_path, {"ops/bad.py": (
            "import numpy as np\n"
            "def f(col):\n"
            "    return np.asarray(\n"
            "        col.data)\n"
            "def g(col):\n"
            "    return np.asarray(\n"
            "        col.codes)  # choke-point-ok (host column; no device buffer)\n")},
            ["blocking-fetch"])
        assert [f.line for f in report.failing] == [3]
        assert len(report.suppressed) == 1

    def test_outside_operator_layer_ignored(self, tmp_path):
        report = _lint(tmp_path, {"io/x.py": (
            "import jax\n"
            "def f(x):\n"
            "    return jax.device_get(x)\n")}, ["blocking-fetch"])
        assert report.failing == []

    def test_region_fusible_raw_sync_detected(self, tmp_path):
        """A raw fetch/fetch_scalars inside a ``region_fusible = True``
        operator body breaks the one-prologue-fetch-per-region
        contract; the same call in a non-fusible class is fine."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "from spark_rapids_tpu.utils.metrics import fetch, fetch_scalars\n"
            "class FooExec:\n"
            "    region_fusible = True\n"
            "    def execute(self, ctx):\n"
            "        n = fetch_scalars(ctx.counts)[0]\n"
            "        return fetch(ctx.batch)\n"
            "class BarExec:\n"
            "    region_fusible = False\n"
            "    def execute(self, ctx):\n"
            "        return fetch(ctx.batch)\n")}, ["blocking-fetch"])
        assert sorted(f.line for f in report.failing) == [5, 6]
        assert all("region prologue" in f.message for f in report.failing)

    def test_region_fusible_fusion_ok_suppresses(self, tmp_path):
        """``# fusion-ok (<why>)`` exempts a sync that genuinely cannot
        ride the prologue; the prologue APIs themselves never flag."""
        report = _lint(tmp_path, {"plan/ok.py": (
            "from spark_rapids_tpu.utils.metrics import (\n"
            "    fetch, region_scalars, stage_scalars)\n"
            "class FooExec:\n"
            "    region_fusible = True\n"
            "    def execute(self, ctx):\n"
            "        stage_scalars('k', ctx.counts)\n"
            "        n = region_scalars(ctx.counts)[0]\n"
            "        tail = fetch(ctx.tail)  # fusion-ok (end-of-stream tail: one batched fetch by construction)\n"
            "        return n, tail\n")}, ["blocking-fetch"])
        assert report.failing == []
        assert len(report.suppressed) == 1


class TestSpanTiming:
    def test_aliased_clock_import(self, tmp_path):
        """`from time import perf_counter` was invisible to the
        `time.perf_counter(` regex."""
        report = _lint(tmp_path, {"parallel/bad.py": (
            "from time import perf_counter as pc\n"
            "t0 = pc()\n")}, ["span-timing"])
        assert [f.line for f in report.failing] == [2]


class TestCtxThreads:
    def test_evidence_beyond_regex_window(self, tmp_path):
        """copy_context evidence 5+ lines from the creation site was a
        false POSITIVE for the ±3-line regex window; the AST pass
        scopes evidence to the enclosing function."""
        src = (
            "import contextvars, threading\n"
            "def spawn(fn):\n"
            "    cctx = contextvars.copy_context()\n"
            "    a = 1\n"
            "    b = 2\n"
            "    c = 3\n"
            "    d = 4\n"
            "    th = threading.Thread(target=lambda: cctx.run(fn))\n"
            "    th.start()\n")
        report = _lint(tmp_path, {"runtime/pool.py": src},
                       ["ctx-threads"])
        assert report.failing == []

    def test_detect_and_reasoned_suppress(self, tmp_path):
        report = _lint(tmp_path, {"runtime/bad.py": (
            "import threading\n"
            "def spawn(fn):\n"
            "    threading.Thread(target=fn).start()\n"
            "def ok(fn):\n"
            "    threading.Thread(target=fn).start()  # ctx-ok (process-lifetime control plane)\n")},
            ["ctx-threads"])
        assert [f.line for f in report.failing] == [3]
        assert len(report.suppressed) == 1


class TestCacheKeys:
    def test_aliased_constructor_and_multiline_literal(self, tmp_path):
        """Both regex false-negative classes: an aliased CacheKey
        import and a literal key split across lines."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "from ..cache.keys import CacheKey as CK\n"
            "def f(cache, schema):\n"
            "    k = CK('scan', (), None, None)\n"
            "    return cache.lookup_scan(\n"
            "        ('adhoc',\n"
            "         'tuple'), schema)\n")}, ["cache-keys"])
        assert sorted(f.line for f in report.failing) == [3, 4]

    def test_keys_module_itself_exempt(self, tmp_path):
        report = _lint(tmp_path, {"cache/keys.py": (
            "class CacheKey:\n"
            "    pass\n"
            "def scan_key():\n"
            "    return CacheKey()\n")}, ["cache-keys"])
        assert report.failing == []


class TestFaultPaths:
    def test_multiline_except_sleep_pair(self, tmp_path):
        """A sleep 10 lines into the handler suite: past the regex
        scanner's 8-line window, inside the AST handler scope."""
        filler = "".join(f"        x{i} = {i}\n" for i in range(10))
        report = _lint(tmp_path, {"io/bad.py": (
            "import time\n"
            "def r():\n"
            "    try:\n"
            "        return g()\n"
            "    except OSError:\n"
            + filler +
            "        time.sleep(0.1)\n")}, ["fault-paths"])
        assert len(report.failing) == 1
        assert "ad-hoc retry" in report.failing[0].message
        assert report.failing[0].line == 16

    def test_swallowed_fault_marker_on_pass_line(self, tmp_path):
        report = _lint(tmp_path, {"io/x.py": (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass  # fault-ok (best-effort hint)\n"
            "    try:\n"
            "        g()\n"
            "    except BaseException:\n"
            "        pass\n")}, ["fault-paths"])
        assert [f.line for f in report.failing] == [8]
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# new passes
# ---------------------------------------------------------------------------

class TestReleasePaths:
    def test_leaked_handle_detected(self, tmp_path):
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    h.get()\n")}, ["release-paths"])
        assert len(report.failing) == 1
        assert "never released" in report.failing[0].message

    def test_straight_line_release_flagged(self, tmp_path):
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    work(h)\n"
            "    h.close()\n")}, ["release-paths"])
        assert len(report.failing) == 1
        assert "straight-line" in report.failing[0].message

    def test_finally_release_clean(self, tmp_path):
        report = _lint(tmp_path, {"plan/ok.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    try:\n"
            "        work(h)\n"
            "    finally:\n"
            "        h.close()\n")}, ["release-paths"])
        assert report.failing == []

    def test_exit_edge_between_acquire_and_finally(self, tmp_path):
        """CFG-lite: a return between acquisition and its protecting
        try/finally is a leak edge."""
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(catalog, b, flag):\n"
            "    h = catalog.register(b)\n"
            "    if flag:\n"
            "        return None\n"
            "    try:\n"
            "        return work(h)\n"
            "    finally:\n"
            "        h.close()\n")}, ["release-paths"])
        assert len(report.failing) == 1
        assert report.failing[0].line == 4
        assert "leaks" in report.failing[0].message

    def test_escape_and_with_are_clean(self, tmp_path):
        report = _lint(tmp_path, {"plan/ok.py": (
            "def f(catalog, b, out):\n"
            "    h = catalog.register(b)\n"
            "    out.append(h)\n"
            "def g(sem):\n"
            "    with sem.acquire():\n"
            "        pass\n"
            "def r(cache, key):\n"
            "    hit = cache.lookup_broadcast(key)\n"
            "    return hit\n")}, ["release-paths"])
        assert report.failing == []

    def test_paired_void_quota(self, tmp_path):
        report = _lint(tmp_path, {"server/bad.py": (
            "def f(quotas, tenant):\n"
            "    quotas.acquire(tenant)\n"
            "    work()\n"
            "    quotas.release(tenant)\n"
            "def ok(quotas, tenant):\n"
            "    quotas.acquire(tenant)\n"
            "    try:\n"
            "        work()\n"
            "    finally:\n"
            "        quotas.release(tenant)\n")}, ["release-paths"])
        assert [f.line for f in report.failing] == [2]
        assert "finally" in report.failing[0].message


class TestLockDiscipline:
    def test_blocking_under_lock(self, tmp_path):
        report = _lint(tmp_path, {"service/bad.py": (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self, sock):\n"
            "        with self._lock:\n"
            "            sock.recv(4096)\n")}, ["lock-discipline"])
        assert len(report.failing) == 1
        assert "sock.recv" in report.failing[0].message

    def test_cv_self_wait_not_flagged(self, tmp_path):
        report = _lint(tmp_path, {"service/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "    def f(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait()\n")}, ["lock-discipline"])
        assert report.failing == []

    def test_blocking_through_helper(self, tmp_path):
        """Interprocedural summary: the blocking call hides one level
        down in a same-module helper."""
        report = _lint(tmp_path, {"service/bad.py": (
            "import threading\n"
            "def _pull(sock):\n"
            "    return sock.recv(4096)\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self, sock):\n"
            "        with self._lock:\n"
            "            return _pull(sock)\n")}, ["lock-discipline"])
        assert len(report.failing) == 1
        assert "reaches blocking" in report.failing[0].message

    def test_lock_order_cycle(self, tmp_path):
        report = _lint(tmp_path, {"cache/bad.py": (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def ba(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n")}, ["lock-discipline"])
        cyc = [f for f in report.failing if "cycle" in f.message]
        assert len(cyc) == 2  # one per participating edge
        assert "one global order" in cyc[0].message

    def test_consistent_order_no_cycle(self, tmp_path):
        report = _lint(tmp_path, {"cache/ok.py": (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def ab2(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n")}, ["lock-discipline"])
        assert report.failing == []


_CONF_FIXTURE = {
    "config.py": (
        "def register(key, default, doc, **kw):\n"
        "    return key\n"
        "A = register('spark.rapids.tpu.a', 1, 'used and documented')\n"
        "B = register('spark.rapids.tpu.b', 1, 'internal',\n"
        "             internal=True)\n"
        "ORPHAN = register('spark.rapids.tpu.orphan', 1, 'dead')\n"),
    "user.py": (
        "from .config import B\n"
        "def f(conf, tier):\n"
        "    x = conf['spark.rapids.tpu.a']\n"
        "    y = conf['spark.rapids.tpu.nope']\n"
        "    z = conf[f'spark.rapids.tpu.{tier}.enabled']\n"
        "    return x, y, z, B\n"),
}


class TestConfRegistry:
    def _run(self, tmp_path, docs: str):
        root = _tree(tmp_path, _CONF_FIXTURE)
        os.makedirs(os.path.join(root, "docs"), exist_ok=True)
        with open(os.path.join(root, "docs", "configs.md"), "w") as f:
            f.write(docs)
        return lint_run(root, roots=("spark_rapids_tpu",),
                        rules=["conf-registry"])

    def test_unknown_dynamic_orphan_and_docs(self, tmp_path):
        report = self._run(
            tmp_path,
            "| spark.rapids.tpu.a | 1 | doc |\n"
            "| spark.rapids.tpu.orphan | 1 | doc |\n"
            "| spark.rapids.tpu.stale | 1 | doc |\n")
        msgs = sorted(f.message for f in report.failing)
        assert any("'spark.rapids.tpu.nope' is not registered" in m
                   for m in msgs)
        assert any("f-string" in m for m in msgs)
        assert any("'spark.rapids.tpu.orphan' is orphaned" in m
                   for m in msgs)
        assert any("no longer registered" in m for m in msgs)
        # the internal key B needs no docs entry and is referenced
        assert not any("'spark.rapids.tpu.b'" in m for m in msgs)

    def test_missing_doc_entry(self, tmp_path):
        report = self._run(tmp_path,
                           "| spark.rapids.tpu.orphan | 1 | doc |\n")
        assert any("missing from docs/configs.md" in f.message
                   and "'spark.rapids.tpu.a'" in f.message
                   for f in report.failing)


# ---------------------------------------------------------------------------
# engine: suppression hygiene, baseline workflow, cache, CLI
# ---------------------------------------------------------------------------

class TestEngine:
    def test_srtlint_ignore_syntax_and_reason_required(self, tmp_path):
        report = _lint(tmp_path, {"plan/x.py": (
            "import jax\n"
            "a = jax.device_get(1)  # srtlint: ignore[blocking-fetch] (test seed, not a device value)\n"
            "b = jax.device_get(2)  # srtlint: ignore[blocking-fetch]\n")},
            ["blocking-fetch"])
        assert [f.line for f in report.failing] == [3]
        assert "no reason" in report.failing[0].message
        assert [f.line for f in report.suppressed] == [2]
        assert "test seed" in report.suppressed[0].suppress_reason

    def test_baseline_workflow(self, tmp_path):
        files = {"plan/bad.py": ("import jax\n"
                                 "a = jax.device_get(1)\n")}
        root = _tree(tmp_path, files)
        bl = str(tmp_path / "baseline.json")
        report = lint_run(root, roots=("spark_rapids_tpu",),
                          rules=["blocking-fetch"], baseline_path=bl)
        assert len(report.failing) == 1
        engine.write_baseline(report.failing, bl)
        again = lint_run(root, roots=("spark_rapids_tpu",),
                         rules=["blocking-fetch"], baseline_path=bl)
        assert again.failing == []
        assert len(again.baselined) == 1
        # line drift does not invalidate the baseline entry
        files = {"plan/bad.py": ("import jax\n# pushed down\n"
                                 "a = jax.device_get(1)\n")}
        root = _tree(tmp_path, files)
        moved = lint_run(root, roots=("spark_rapids_tpu",),
                         rules=["blocking-fetch"], baseline_path=bl)
        assert moved.failing == []
        assert len(moved.baselined) == 1

    def test_cli_exit_codes_and_json(self, tmp_path, capsys):
        root = _tree(tmp_path, {"plan/bad.py": (
            "import jax\na = jax.device_get(1)\n")})
        assert engine.main(["--repo", root, "--json"]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["counts"]["failing"] == 1
        root2 = _tree(tmp_path / "clean", {"plan/ok.py": "x = 1\n"})
        assert engine.main(["--repo", root2]) == 0
        assert engine.main(["--explain", "lock-discipline"]) == 0
        assert "lock-acquisition graph" in capsys.readouterr().out
        assert engine.main(["--explain", "nope"]) == 2

    def test_explain_covers_all_thirteen_rules(self):
        rules = engine.available_rules()
        assert rules == ["blocking-fetch", "span-timing", "ctx-threads",
                         "cache-keys", "fault-paths", "release-paths",
                         "lock-discipline", "shutdown-paths",
                         "shared-state-races", "typestate",
                         "protocol-conformance", "metrics-registry",
                         "conf-registry"]
        for r in rules:
            assert r in engine.explain_rule(r)

    def test_parse_error_is_a_finding(self, tmp_path):
        report = _lint(tmp_path, {"plan/broken.py": "def f(:\n"},
                       ["blocking-fetch"])
        assert [f.rule for f in report.failing] == ["parse-error"]


# ---------------------------------------------------------------------------
# PR 12 passes: races, typestate, protocol conformance
# ---------------------------------------------------------------------------

# the seeded unguarded-counter race: one accept loop spawning handler
# threads in a while loop (a MULTI-instance root), both bumping a
# counter the snapshot reads — no lock anywhere
_RACE_BAD = (
    "import threading\n"
    "class Door:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.queries_total = 0\n"
    "    def start(self):\n"
    "        self._th = threading.Thread(target=self._accept_loop)\n"
    "        self._th.start()\n"
    "    def _accept_loop(self):\n"
    "        while True:\n"
    "            th = threading.Thread(target=self._handle)\n"
    "            th.start()\n"
    "    def _handle(self):\n"
    "        self.queries_total += 1\n"
    "    def close(self):\n"
    "        self._th.join(timeout=2.0)\n")


class TestSharedStateRaces:
    def test_unguarded_counter_across_handler_threads(self, tmp_path):
        report = _lint(tmp_path, {"server/bad.py": _RACE_BAD},
                       ["shared-state-races"])
        assert len(report.failing) == 1
        f = report.failing[0]
        assert "queries_total" in f.message and f.line == 14
        assert "[xN]" in f.message  # the multi-instance handler root

    def test_lock_guarded_counter_clean(self, tmp_path):
        src = _RACE_BAD.replace(
            "        self.queries_total += 1\n",
            "        with self._lock:\n"
            "            self.queries_total += 1\n")
        report = _lint(tmp_path, {"server/ok.py": src},
                       ["shared-state-races"])
        # the write is guarded; no OTHER access exists to pair with it
        assert report.failing == []

    def test_guarded_write_vs_bare_read_flagged_at_read(self, tmp_path):
        src = _RACE_BAD.replace(
            "        self.queries_total += 1\n",
            "        with self._lock:\n"
            "            self.queries_total += 1\n").replace(
            "    def close(self):\n",
            "    def snapshot(self):\n"
            "        return self.queries_total\n"
            "    def close(self):\n")
        report = _lint(tmp_path, {"server/bad.py": src},
                       ["shared-state-races"])
        assert len(report.failing) == 1
        assert report.failing[0].line == 17  # the bare read site

    def test_immutable_after_publish_and_single_writer_clean(
            self, tmp_path):
        report = _lint(tmp_path, {"server/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self.addr = ('h', 1)\n"       # init-only write
            "        self.count = 0\n"
            "    def start(self):\n"
            "        self._th = threading.Thread(target=self._loop)\n"
            "        self._th.start()\n"
            "    def _loop(self):\n"
            "        self.count += 1\n"            # single-writer root
            "    def peer(self):\n"
            "        return self.addr\n"
            "    def close(self):\n"
            "        self._th.join(timeout=2.0)\n")},
            ["shared-state-races"])
        assert report.failing == []

    def test_reasoned_suppression(self, tmp_path):
        src = _RACE_BAD.replace(
            "        self.queries_total += 1\n",
            "        self.queries_total += 1  # srtlint: ignore[shared-state-races] (GIL-atomic telemetry bump; a lost update skews a counter, never correctness)\n")
        report = _lint(tmp_path, {"server/ok.py": src},
                       ["shared-state-races"])
        assert report.failing == []
        assert len(report.suppressed) == 1
        assert "telemetry" in report.suppressed[0].suppress_reason

    def test_regression_endpoint_counter_guards(self, tmp_path):
        """PR 12 true positive: the front door's lifetime counters were
        bumped by N connection handlers with no lock.  Un-guarding the
        REAL endpoint.py must re-fire the pass — the fix cannot
        silently regress."""
        real = open(os.path.join(
            REPO, "spark_rapids_tpu", "server", "endpoint.py")).read()
        bad = real.replace(
            "                with self._lock:\n"
            "                    self.streamed_bytes += n\n",
            "                self.streamed_bytes += n\n")
        assert bad != real  # the guarded shape exists to revert
        report = _lint(tmp_path, {"server/endpoint.py": bad},
                       ["shared-state-races"])
        assert any("streamed_bytes" in f.message
                   for f in report.failing), \
            [f.message for f in report.failing]
        # and the guarded original is clean
        clean = _lint(tmp_path / "c", {"server/endpoint.py": real},
                      ["shared-state-races"])
        assert clean.failing == []

    def test_regression_prepared_cache_miss_guard(self, tmp_path):
        """PR 12 true positive: PreparedCache.misses bumped between the
        two lock blocks.  Reverting the guard (with the real endpoint
        supplying the connection-handler thread roots) re-fires."""
        real_ep = open(os.path.join(
            REPO, "spark_rapids_tpu", "server", "endpoint.py")).read()
        real_pc = open(os.path.join(
            REPO, "spark_rapids_tpu", "server", "prepared.py")).read()
        bad = real_pc.replace(
            "        with self._lock:\n"
            "            self.misses += 1\n",
            "        self.misses += 1\n")
        assert bad != real_pc
        report = _lint(tmp_path, {"server/endpoint.py": real_ep,
                                  "server/prepared.py": bad},
                       ["shared-state-races"])
        assert any("misses" in f.message for f in report.failing), \
            [f.message for f in report.failing]
        clean = _lint(tmp_path / "c", {"server/endpoint.py": real_ep,
                                       "server/prepared.py": real_pc},
                      ["shared-state-races"])
        assert clean.failing == []


class TestTypestate:
    def test_use_after_close_on_spooled_stream(self, tmp_path):
        report = _lint(tmp_path, {"server/bad.py": (
            "def f(mem, d):\n"
            "    s = ResultStream('q', mem, d)\n"
            "    s.put(b'x')\n"
            "    s.close()\n"
            "    s.put(b'y')\n")}, ["typestate"])
        assert len(report.failing) == 1
        assert "use-after-close" in report.failing[0].message
        assert report.failing[0].line == 5

    def test_double_release_on_cached_build_handle(self, tmp_path):
        report = _lint(tmp_path, {"plan/bad.py": (
            "def f(cache, key):\n"
            "    h = cache.lookup_broadcast(key)\n"
            "    h.close()\n"
            "    h.close()\n")}, ["typestate"])
        assert len(report.failing) == 1
        assert "double-release" in report.failing[0].message

    def test_maybe_closed_branch_not_flagged(self, tmp_path):
        """A finding needs the op invalid in EVERY possible state —
        close on one branch only is a maybe, not a definite bug."""
        report = _lint(tmp_path, {"plan/ok.py": (
            "def f(cache, key, flag):\n"
            "    h = cache.lookup_broadcast(key)\n"
            "    if flag:\n"
            "        h.close()\n"
            "        return None\n"
            "    out = h.get()\n"
            "    h.close()\n"
            "    return out\n")}, ["typestate"])
        assert report.failing == []

    def test_finally_close_then_no_touch_clean(self, tmp_path):
        report = _lint(tmp_path, {"memory/ok.py": (
            "def f(catalog, b):\n"
            "    h = catalog.register(b)\n"
            "    try:\n"
            "        return h.get()\n"
            "    finally:\n"
            "        h.close()\n")}, ["typestate"])
        assert report.failing == []

    def test_escape_of_closed_handle_flagged(self, tmp_path):
        report = _lint(tmp_path, {"memory/bad.py": (
            "def f(catalog, b, out):\n"
            "    h = catalog.register(b)\n"
            "    h.close()\n"
            "    out.adopt(h)\n")}, ["typestate"])
        assert len(report.failing) == 1
        assert "escapes" in report.failing[0].message

    def test_use_before_init_two_phase(self, tmp_path):
        report = _lint(tmp_path, {"server/bad.py": (
            "def f(session):\n"
            "    d = SqlFrontDoor(session)\n"
            "    d.begin_drain()\n"
            "def ok(session):\n"
            "    d = SqlFrontDoor(session)\n"
            "    d.start()\n"
            "    d.begin_drain()\n")}, ["typestate"])
        assert [f.line for f in report.failing] == [3]
        assert "use-before-init" in report.failing[0].message

    def test_reasoned_suppression(self, tmp_path):
        report = _lint(tmp_path, {"server/ok.py": (
            "def f(mem, d):\n"
            "    s = ResultStream('q', mem, d)\n"
            "    s.close()\n"
            "    s.put(b'y')  # srtlint: ignore[typestate] (put on a closed stream is the producer's documented stop signal in this probe)\n")},
            ["typestate"])
        assert report.failing == []
        assert len(report.suppressed) == 1


_PROTO_FIXTURE = {
    "server/protocol.py": (
        'REQ_HELLO = b"h"\n'
        'RSP_WELCOME = b"W"\n'
        'RSP_GOAWAY = b"G"\n'     # sent below, never decoded
        'RSP_UNUSED = b"U"\n'     # defined, never sent
        'ERROR_CODES = ("BAD_REQUEST", "DEAD_CODE")\n'
        "class WireError(RuntimeError):\n"
        "    def __init__(self, code, msg):\n"
        "        self.code = code\n"),
    "server/endpoint.py": (
        "from . import protocol as P\n"
        "from .protocol import WireError\n"
        "def serve(conn, bad):\n"
        "    ftype, payload = P.recv_frame(conn, expect=(P.REQ_HELLO,))\n"
        "    P.send_frame(conn, P.RSP_WELCOME)\n"
        "    P.send_frame(conn, P.RSP_GOAWAY)\n"
        "    if bad:\n"
        "        raise WireError('BAD_REQUEST', 'malformed')\n"
        "    raise WireError('NOT_IN_REGISTRY', 'oops')\n"),
    "server/client.py": (
        "from . import protocol as P\n"
        "def hello(sock):\n"
        "    P.send_frame(sock, P.REQ_HELLO)\n"
        "    ftype, payload = P.recv_frame(sock,\n"
        "                                  expect=(P.RSP_WELCOME,))\n"
        "    return ftype\n"
        "def dispatch(e):\n"
        "    return e.code == 'TYPO_CODE'\n"),
}


class TestProtocolConformance:
    def test_wire_drift_classes(self, tmp_path):
        report = _lint(tmp_path, _PROTO_FIXTURE,
                       ["protocol-conformance"])
        msgs = sorted(f.message for f in report.failing)
        # sent but no decoder handles it (the GOAWAY drift class)
        assert any("RSP_GOAWAY is sent here but no decoder" in m
                   for m in msgs)
        # defined but nobody sends it
        assert any("dead frame type: RSP_UNUSED" in m for m in msgs)
        # constructed code missing from the registry
        assert any("'NOT_IN_REGISTRY' is constructed here" in m
                   for m in msgs)
        # registered code nobody constructs
        assert any("dead error code: 'DEAD_CODE'" in m for m in msgs)
        # dispatch comparison against an unregistered code
        assert any("'TYPO_CODE'" in m and "never match" in m
                   for m in msgs)
        assert len(report.failing) == 5

    def test_unhandled_error_code_fixed_by_registration(self, tmp_path):
        files = dict(_PROTO_FIXTURE)
        files["server/protocol.py"] = files["server/protocol.py"] \
            .replace('("BAD_REQUEST", "DEAD_CODE")',
                     '("NOT_IN_REGISTRY", "TYPO_CODE", "BAD_REQUEST")')
        files["server/endpoint.py"] = files["server/endpoint.py"] \
            .replace("    P.send_frame(conn, P.RSP_GOAWAY)\n", "") \
            .replace("raise WireError('NOT_IN_REGISTRY', 'oops')",
                     "raise WireError('BAD_REQUEST', 'oops')")
        files["server/client.py"] = files["server/client.py"] \
            .replace("'TYPO_CODE'", "'BAD_REQUEST'")
        report = _lint(tmp_path, files, ["protocol-conformance"])
        msgs = sorted(f.message for f in report.failing)
        # only the dead vocabulary remains
        assert all("dead" in m for m in msgs), msgs

    def test_dcn_op_vocabulary(self, tmp_path):
        report = _lint(tmp_path, {"parallel/dcn.py": (
            'DCN_OPS = ("fetch", "journal", "ghost")\n'
            "def client(sock):\n"
            "    _send(sock, {'op': 'fetch'})\n"
            "    _send(sock, {'op': 'journal'})\n"
            "    _send(sock, {'op': 'mystery'})\n"
            "def serve(msg):\n"
            "    op = msg.get('op')\n"
            "    if op == 'fetch':\n"
            "        return 1\n"
            "    if op != 'journal':\n"
            "        return 0\n")}, ["protocol-conformance"])
        msgs = sorted(f.message for f in report.failing)
        assert any("'mystery' is sent here but no dispatch" in m
                   for m in msgs)
        assert any("'mystery' is sent here but missing from DCN_OPS"
                   in m for m in msgs)
        assert any("dead DCN op: 'ghost'" in m for m in msgs)

    def test_reasoned_suppression(self, tmp_path):
        files = dict(_PROTO_FIXTURE)
        files["server/endpoint.py"] = files["server/endpoint.py"] \
            .replace(
                "    P.send_frame(conn, P.RSP_GOAWAY)\n",
                "    P.send_frame(conn, P.RSP_GOAWAY)  # srtlint: ignore[protocol-conformance] (decoded by the out-of-tree ops client)\n")
        report = _lint(tmp_path, files, ["protocol-conformance"])
        assert not any("RSP_GOAWAY" in f.message for f in report.failing)
        assert any("RSP_GOAWAY" in f.message for f in report.suppressed)

    def test_real_registries_exist(self):
        """The canonical vocabularies the pass checks against."""
        from spark_rapids_tpu.server import protocol as P
        from spark_rapids_tpu.parallel import dcn
        assert "DRAINING" in P.ERROR_CODES
        assert set(dcn._COORD_OPS) < set(dcn.DCN_OPS)
        assert "fetch" in dcn.DCN_OPS and "journal" in dcn.DCN_OPS


_METRICS_FIXTURE = {
    "utils/telemetry.py": (
        "METRICS = (\n"
        '    ("hits_total", "counter", "", "hits"),\n'
        '    ("dead_gauge", "gauge", "", "nobody emits this"),\n'
        '    ("folded_total", "counter", "", "fold target"),\n'
        ")\n"
        "_QS_FOLD = (\n"
        '    ("hits", "folded_total"),\n'
        ")\n"
        "def count(name, amount=1, **labels):\n"
        "    pass\n"
        "def gauge_set(name, value, **labels):\n"
        "    pass\n"
        "def observe(name, value, **labels):\n"
        "    pass\n"),
    "service/user.py": (
        "from ..utils import telemetry\n"
        "def f(kind):\n"
        "    telemetry.count('hits_total')\n"
        "    telemetry.count('unregistered_total')\n"
        "    telemetry.gauge_set('made_' + kind, 1.0)\n"),
}


class TestMetricsRegistry:
    def test_two_way_vocabulary(self, tmp_path):
        report = _lint(tmp_path, _METRICS_FIXTURE, ["metrics-registry"])
        msgs = sorted(f.message for f in report.failing)
        # unregistered at a call site
        assert any("'unregistered_total' is emitted here but not "
                   "registered" in m for m in msgs)
        # runtime-assembled name
        assert any("assembled at runtime" in m for m in msgs)
        # registered but never emitted (fold targets count as emitted)
        assert any("dead metric vocabulary: 'dead_gauge'" in m
                   for m in msgs)
        assert not any("folded_total" in m for m in msgs)
        assert not any("'hits_total'" in m for m in msgs)
        assert len(report.failing) == 3

    def test_registration_fixes_use_and_emitter_fixes_dead(
            self, tmp_path):
        files = dict(_METRICS_FIXTURE)
        files["utils/telemetry.py"] = files["utils/telemetry.py"] \
            .replace('    ("dead_gauge", "gauge", "", "nobody emits '
                     'this"),\n',
                     '    ("unregistered_total", "counter", "", '
                     '"now registered"),\n')
        files["service/user.py"] = (
            "from ..utils import telemetry\n"
            "def f():\n"
            "    telemetry.count('hits_total')\n"
            "    telemetry.count('unregistered_total')\n")
        report = _lint(tmp_path, files, ["metrics-registry"])
        assert report.failing == [], [f.message for f in report.failing]

    def test_reasoned_suppression(self, tmp_path):
        files = dict(_METRICS_FIXTURE)
        files["service/user.py"] = files["service/user.py"] \
            .replace(
                "    telemetry.count('unregistered_total')\n",
                "    telemetry.count('unregistered_total')  # srtlint: ignore[metrics-registry] (emitted for an out-of-tree dashboard)\n") \
            .replace(
                "    telemetry.gauge_set('made_' + kind, 1.0)\n", "")
        files["utils/telemetry.py"] = files["utils/telemetry.py"] \
            .replace('    ("dead_gauge", "gauge", "", "nobody emits '
                     'this"),\n', "")
        report = _lint(tmp_path, files, ["metrics-registry"])
        assert report.failing == [], [f.message for f in report.failing]
        assert any("unregistered_total" in f.message
                   for f in report.suppressed)

    def test_real_registry_exists(self):
        """The canonical table the pass checks against, and its
        runtime enforcement."""
        from spark_rapids_tpu.utils import telemetry
        names = {m[0] for m in telemetry.METRICS}
        assert "queries_shed_total" in names
        assert "slo_burn_rate" in names
        for _field, metric in telemetry._QS_FOLD:
            assert metric in names, metric
        with pytest.raises(KeyError):
            telemetry.count("never_registered_total")


_MARKS_FIXTURE = {
    "utils/telemetry.py": (
        "METRICS = (\n"
        '    ("hits_total", "counter", "", "hits"),\n'
        ")\n"
        "_QS_FOLD = ()\n"
        "def count(name, amount=1, **labels):\n"
        "    pass\n"),
    "utils/tracing.py": (
        'MARK_PREFIXES = ("perf:", "compile:")\n'
        "MARKS = (\n"
        '    ("perf:anomaly", "root-cause verdict"),\n'
        '    ("compile:storm", "storm detector"),\n'
        '    ("compile:dead", "nobody emits this"),\n'
        ")\n"
        "def mark(op_id, name, cat='mark', **args):\n"
        "    pass\n"
        "def record(op_id, name, cat, t0, dur, **args):\n"
        "    pass\n"),
    "utils/user.py": (
        "from . import telemetry, tracing\n"
        "def f(tr):\n"
        "    telemetry.count('hits_total')\n"
        "    tracing.mark(None, 'perf:anomaly', 'mark')\n"
        "    tr.add_event(None, 'compile:storm', 'compile', 0.0, 0.0)\n"
        "    tr.add_event(None, 'perf:bogus', 'mark', 0.0, 0.0)\n"
        "    tracing.mark(None, 'query:free_form')\n"),
}


class TestMarkVocabulary:
    """The metrics-registry pass's governed trace-mark leg (the
    flight recorder's ``perf:`` / ``compile:`` namespaces)."""

    def test_two_way_mark_vocabulary(self, tmp_path):
        report = _lint(tmp_path, _MARKS_FIXTURE, ["metrics-registry"])
        msgs = sorted(f.message for f in report.failing)
        # a governed-prefix mark minted at an emit site (add_event
        # form) without a MARKS entry
        assert any("'perf:bogus' is emitted here but not registered"
                   in m for m in msgs)
        # a MARKS entry nobody emits
        assert any("dead mark vocabulary: 'compile:dead'" in m
                   for m in msgs)
        # registered marks emitted via tracing.mark AND .add_event
        # both count as used; ungoverned namespaces stay free-form
        assert not any("perf:anomaly" in m for m in msgs)
        assert not any("compile:storm" in m for m in msgs)
        assert not any("query:free_form" in m for m in msgs)
        assert len(report.failing) == 2, msgs

    def test_registration_and_suppression(self, tmp_path):
        files = dict(_MARKS_FIXTURE)
        files["utils/tracing.py"] = files["utils/tracing.py"].replace(
            '    ("compile:dead", "nobody emits this"),\n', "")
        files["utils/user.py"] = files["utils/user.py"].replace(
            "    tr.add_event(None, 'perf:bogus', 'mark', 0.0, 0.0)\n",
            "    tr.add_event(None, 'perf:bogus', 'mark', 0.0, 0.0)"
            "  # srtlint: ignore[metrics-registry] (prototyped mark "
            "for an out-of-tree consumer)\n")
        report = _lint(tmp_path, files, ["metrics-registry"])
        assert report.failing == [], [f.message for f in report.failing]
        assert any("perf:bogus" in f.message
                   for f in report.suppressed)

    def test_fixture_trees_without_tracing_stay_exempt(self, tmp_path):
        """A tree with no utils/tracing.py (older trees, other lint
        fixtures) gets no mark findings at all."""
        files = {k: v for k, v in _MARKS_FIXTURE.items()
                 if k != "utils/tracing.py"}
        report = _lint(tmp_path, files, ["metrics-registry"])
        assert report.failing == [], [f.message for f in report.failing]

    def test_real_mark_vocabulary(self):
        """The canonical MARKS table governs exactly the recorder's
        namespaces, and every entry is under a governed prefix."""
        from spark_rapids_tpu.utils import tracing
        names = {m[0] for m in tracing.MARKS}
        assert "perf:anomaly" in names
        assert "compile:storm" in names
        for name in names:
            assert name.startswith(tracing.MARK_PREFIXES), name


class TestBaselineDrift:
    def test_rewrap_keeps_baseline_entry(self, tmp_path):
        """A pure reformat (re-indent + re-wrap across lines) of a
        baselined statement keeps its entry alive — the key hashes the
        whole statement with whitespace stripped, not the first line."""
        files = {"plan/bad.py": (
            "import jax\n"
            "a = jax.device_get(make_value(1, 2))\n")}
        root = _tree(tmp_path, files)
        bl = str(tmp_path / "baseline.json")
        report = lint_run(root, roots=("spark_rapids_tpu",),
                          rules=["blocking-fetch"], baseline_path=bl)
        engine.write_baseline(report.failing, bl)
        (tmp_path / "spark_rapids_tpu" / "plan" / "bad.py").write_text(
            "import jax\n"
            "a = jax.device_get(\n"
            "        make_value(1,\n"
            "                   2))\n")
        moved = lint_run(root, roots=("spark_rapids_tpu",),
                         rules=["blocking-fetch"], baseline_path=bl)
        assert moved.failing == []
        assert len(moved.baselined) == 1


class TestIncremental:
    def _seed(self, tmp_path):
        files = {
            "plan/a.py": "import jax\ndef f(x):\n    return x\n",
            "plan/b.py": ("from .a import f\n"
                          "def g(x):\n    return f(x)\n"),
            "ops/c.py": ("import numpy as np\n"
                         "def h(col):\n"
                         "    return np.asarray(col.data)  # choke-point-ok (host column; fixture)\n"),
        }
        return _tree(tmp_path, files)

    def test_cold_then_noop_then_edit(self, tmp_path):
        from tools.srtlint.incremental import run_incremental
        root = self._seed(tmp_path)
        cold = run_incremental(root, roots=("spark_rapids_tpu",))
        assert cold.failing == []
        assert len(cold.suppressed) == 1   # the choke-point-ok marker
        assert cold.incremental["cone"] == 3
        # unchanged tree: nothing re-analyzed, cache carries reasons
        noop = run_incremental(root, roots=("spark_rapids_tpu",))
        assert noop.incremental["cone"] == 0
        assert noop.incremental["parsed"] == 0
        assert noop.failing == []
        assert len(noop.suppressed) == 1
        assert noop.suppressed[0].suppress_reason
        # a one-file edit introducing a finding re-verifies without a
        # full re-analysis: only the edited file (plus its reverse-
        # dependency cone) is re-parsed
        (tmp_path / "spark_rapids_tpu" / "plan" / "a.py").write_text(
            "import jax\ndef f(x):\n    return jax.device_get(x)\n")
        edit = run_incremental(root, roots=("spark_rapids_tpu",))
        assert [f.path for f in edit.failing] == ["spark_rapids_tpu/plan/a.py"]
        assert edit.incremental["changed"] == 1
        assert edit.incremental["cone"] == 2      # a.py + dependent b.py
        # c.py is parsed only because the package-scoped global passes
        # re-run; its per-file verdict (the suppression) comes from the
        # cache, not a re-analysis
        assert len(edit.suppressed) == 1
        assert edit.suppressed[0].suppress_reason

    def test_reverse_dependency_cone_gates_global_passes(self, tmp_path):
        from tools.srtlint import incremental as incr
        root = self._seed(tmp_path)
        incr.run_incremental(root, roots=("spark_rapids_tpu",))
        # an edit outside every global scope... plan/ is inside the
        # races scope (whole package), so races re-runs; but protocol
        # and lock-discipline scopes are untouched and stay cached
        (tmp_path / "spark_rapids_tpu" / "plan" / "a.py").write_text(
            "import jax\ndef f(x):\n    return x + 1\n")
        edit = incr.run_incremental(root, roots=("spark_rapids_tpu",))
        rerun = set(edit.incremental["global_rerun"])
        assert "shared-state-races" in rerun
        assert "protocol-conformance" not in rerun
        assert "lock-discipline" not in rerun

    def test_single_file_edit_faster_than_cold(self):
        """Acceptance: on the REAL tree, a one-file edit re-verifies
        incrementally in well under a full cold scan (no full re-parse
        of the unchanged files' local verdicts)."""
        import shutil
        import tempfile
        import time as _t
        from tools.srtlint.incremental import run_incremental
        with tempfile.TemporaryDirectory() as tmp:
            for root in ("spark_rapids_tpu", "tools"):
                shutil.copytree(os.path.join(REPO, root),
                                os.path.join(tmp, root))
            os.makedirs(os.path.join(tmp, "docs"), exist_ok=True)
            shutil.copy(os.path.join(REPO, "docs", "configs.md"),
                        os.path.join(tmp, "docs", "configs.md"))
            t0 = _t.perf_counter()
            cold = run_incremental(tmp)
            cold_s = _t.perf_counter() - t0
            assert cold.failing == []
            target = os.path.join(tmp, "spark_rapids_tpu", "ops",
                                  "cast.py")
            # the bar: a one-file edit must not pay the cold scan
            # again.  Each attempt appends a FRESH comment line (new
            # content hash -> a genuine changed=1 warm scan), so a
            # CPU-contention spike on one measurement cannot flake the
            # acceptance — the ratio just re-measures.
            timings = []
            for attempt in range(3):
                with open(target, "a") as f:
                    f.write(f"\n# innocuous trailing comment {attempt}\n")
                t0 = _t.perf_counter()
                warm = run_incremental(tmp)
                warm_s = _t.perf_counter() - t0
                assert warm.failing == []
                assert warm.incremental["changed"] == 1
                timings.append(warm_s)
                if warm_s < 0.8 * cold_s:
                    break
            else:
                raise AssertionError(
                    f"one-file edits kept paying the cold scan: warm "
                    f"{timings} vs cold {cold_s}")


class TestSarifAndChanged:
    def test_sarif_output(self, tmp_path, capsys):
        root = _tree(tmp_path, {"plan/bad.py": (
            "import jax\n"
            "a = jax.device_get(1)\n"
            "b = jax.device_get(2)  # choke-point-ok (fixture seed)\n")})
        out = str(tmp_path / "out.sarif")
        rc = engine.main(["--repo", root, "--full", "--sarif", out])
        capsys.readouterr()
        assert rc == 1
        with open(out) as f:
            sarif = json.load(f)
        assert sarif["version"] == "2.1.0"
        run0 = sarif["runs"][0]
        assert run0["tool"]["driver"]["name"] == "srtlint"
        rules = {r["id"] for r in run0["tool"]["driver"]["rules"]}
        assert "shared-state-races" in rules and "typestate" in rules
        levels = {r["level"] for r in run0["results"]}
        assert levels == {"error", "note"}  # failing + suppressed
        sup = [r for r in run0["results"] if r["level"] == "note"]
        assert sup[0]["suppressions"][0]["justification"]

    def test_changed_scopes_findings(self, tmp_path, capsys):
        import subprocess
        root = _tree(tmp_path, {
            "plan/bad.py": "import jax\na = jax.device_get(1)\n",
            "plan/worse.py": "import jax\nb = jax.device_get(2)\n"})
        subprocess.run(["git", "init", "-q"], cwd=root, check=True)
        subprocess.run(["git", "add", "-A"], cwd=root, check=True)
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t", "commit", "-qm", "seed"],
                       cwd=root, check=True)
        # modify ONE of the two offending files
        (tmp_path / "spark_rapids_tpu" / "plan" / "bad.py").write_text(
            "import jax\na = jax.device_get(11)\n")
        rc = engine.main(["--repo", root, "--full", "--changed"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "plan/bad.py" in out
        # the unchanged offender is excluded from the scoped listing
        assert "plan/worse.py" not in out.split("srtlint:")[0]
        assert "1 in changed files" in out


class TestRealTree:
    def test_full_tree_clean_and_within_wall_budget(self):
        """Acceptance: all twelve passes over the real tree, zero
        unsuppressed findings, every suppression reasoned, inside a
        collection-time wall budget."""
        t0 = time.perf_counter()
        report = engine.run(REPO)
        wall = time.perf_counter() - t0
        assert report.failing == [], \
            "\n".join(f"{f.path}:{f.line}: [{f.rule}] {f.message}"
                      for f in report.failing)
        assert report.files > 100
        assert all(f.suppress_reason for f in report.suppressed)
        assert set(report.pass_timings) == set(engine.available_rules())
        assert wall < 30.0, f"full scan took {wall:.1f}s"

    def test_conftest_entry_point_caches(self):
        """The mtime-keyed cache: a second call with an unchanged tree
        must come back from the memo in far under the five regex
        scanners' combined walk time."""
        from tools.srtlint import run_for_pytest
        first = run_for_pytest()
        t0 = time.perf_counter()
        second = run_for_pytest()
        cached_wall = time.perf_counter() - t0
        assert second.failing == first.failing == []
        assert cached_wall < 1.0

    def test_registry_docs_in_sync(self):
        """conf-registry's docs cross-check holds on the real tree —
        docs/configs.md matches TpuConf.help() exactly."""
        from spark_rapids_tpu.config import TpuConf
        with open(os.path.join(REPO, "docs", "configs.md")) as f:
            doc = f.read()
        for line in TpuConf.help().splitlines():
            assert line in doc


class TestShutdownPaths:
    def test_unjoined_attr_thread_detected(self, tmp_path):
        report = _lint(tmp_path, {"service/bad.py": (
            "import threading\n"
            "class S:\n"
            "    def start(self):\n"
            "        self._th = threading.Thread(target=self._loop)\n"
            "        self._th.start()\n"
            "    def close(self):\n"
            "        pass\n")}, ["shutdown-paths"])
        assert [f.line for f in report.failing] == [4]
        assert "never joined" in report.failing[0].message

    def test_join_without_timeout_still_flagged(self, tmp_path):
        """An unbounded join hangs the shutdown a wedged thread was
        supposed to be bounded by."""
        report = _lint(tmp_path, {"server/bad.py": (
            "import threading\n"
            "class S:\n"
            "    def start(self):\n"
            "        self._th = threading.Thread(target=self._loop)\n"
            "        self._th.start()\n"
            "    def close(self):\n"
            "        self._th.join()\n")}, ["shutdown-paths"])
        assert [f.line for f in report.failing] == [4]

    def test_no_handle_escape_detected_and_suppressed(self, tmp_path):
        report = _lint(tmp_path, {"parallel/bad.py": (
            "import threading\n"
            "def fire(fn):\n"
            "    threading.Thread(target=fn).start()\n"
            "def ok(fn):\n"
            "    threading.Thread(target=fn).start()  # srtlint: ignore[shutdown-paths] (hedge loser; socket timeout bounds it)\n")},
            ["shutdown-paths"])
        assert [f.line for f in report.failing] == [3]
        assert "no handle escapes" in report.failing[0].message
        assert len(report.suppressed) == 1

    def test_container_append_joined_in_close_clean(self, tmp_path):
        report = _lint(tmp_path, {"parallel/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def start(self):\n"
            "        t = threading.Thread(target=self._loop)\n"
            "        t.start()\n"
            "        self._threads.append(t)\n"
            "    def close(self):\n"
            "        for t in self._threads:\n"
            "            t.join(timeout=2.0)\n")}, ["shutdown-paths"])
        assert report.failing == []

    def test_dict_store_and_aliased_values_loop_clean(self, tmp_path):
        """The endpoint idiom: store into a dict, join through
        ``list(self._conn_threads.values())`` — two levels of local
        aliasing between the container and the join."""
        report = _lint(tmp_path, {"server/ok.py": (
            "import threading\n"
            "class S:\n"
            "    def accept(self, cid):\n"
            "        th = threading.Thread(target=self._conn)\n"
            "        self._conn_threads[cid] = th\n"
            "        th.start()\n"
            "    def close(self):\n"
            "        threads = list(self._conn_threads.values())\n"
            "        for th in threads:\n"
            "            th.join(timeout=2.0)\n")}, ["shutdown-paths"])
        assert report.failing == []

    def test_same_function_join_clean(self, tmp_path):
        report = _lint(tmp_path, {"parallel/scatter.py": (
            "import threading\n"
            "def fan_out(fns):\n"
            "    ts = []\n"
            "    for fn in fns:\n"
            "        t = threading.Thread(target=fn)\n"
            "        ts.append(t)\n"
            "        t.start()\n"
            "    for t in ts:\n"
            "        t.join(timeout=30)\n")}, ["shutdown-paths"])
        assert report.failing == []

    def test_outside_serving_layers_ignored(self, tmp_path):
        report = _lint(tmp_path, {"runtime/bg.py": (
            "import threading\n"
            "def fire(fn):\n"
            "    threading.Thread(target=fn).start()\n")},
            ["shutdown-paths"])
        assert report.failing == []

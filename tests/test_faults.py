"""Unified fault-injection framework + transient-failure recovery
(spark_rapids_tpu/faults/): injector semantics, retry/backoff/budget,
per-layer recovery (io.read, io.write, shuffle.fragment, dcn.heartbeat,
device.op, cache.lookup), graceful CPU degradation, leak hygiene under
faults, and the chaos differential — results under a seeded fault
schedule must equal the fault-free run with every handle released.
"""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.cache import clear_query_cache, get_query_cache
from spark_rapids_tpu.config import ALL_ENTRIES, TpuConf
from spark_rapids_tpu.faults import (INJECTOR, FaultInjector, InjectedFault,
                                     POINTS, QueryFaulted, TransientFault,
                                     budget_scope, transient_retry)
from spark_rapids_tpu.memory.spill import get_catalog
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.utils.metrics import QueryStats

FAST_BACKOFF = {
    "spark.rapids.tpu.faults.backoff.baseMs": 1.0,
    "spark.rapids.tpu.faults.backoff.maxMs": 8.0,
}


@pytest.fixture()
def faults_session(session):
    """Session with fast backoff; every faults.* key restored after."""
    keys = [k for k in ALL_ENTRIES if k.startswith("spark.rapids.tpu.faults.")]
    for k, v in FAST_BACKOFF.items():
        session.conf.set(k, v)
    yield session
    for k in keys:
        session.conf.unset(k)
    for k in ("spark.rapids.tpu.sql.cache.enabled",
              "spark.rapids.tpu.shuffle.mode",
              "spark.rapids.tpu.sql.trace.enabled"):
        session.conf.unset(k)
    INJECTOR.arm()  # clear any armed schedule/rate
    clear_query_cache()


def _write_pq(tmp_path, name, pdf):
    path = str(tmp_path / name)
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), path)
    return path


def _frame(n=3000, seed=11):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "a": np.arange(n, dtype=np.int64),
        "b": rng.random(n),
        "k": rng.integers(0, 12, n).astype(np.int64),
    })


def _agg_rows(sess, path):
    df = sess.read_parquet(path)
    return sorted(df.filter(F.col("b") < 0.7).group_by("k").agg(
        F.sum(F.col("a")).alias("s"),
        F.count(F.col("b")).alias("c")).collect())


# ---------------------------------------------------------------------------
# Injector semantics.
# ---------------------------------------------------------------------------

class TestInjector:
    def test_schedule_fires_exactly_nth(self):
        inj = FaultInjector()
        inj.arm(schedule="io.read:3")
        fired = []
        for i in range(1, 6):
            try:
                inj.maybe_raise("io.read")
            except InjectedFault:
                fired.append(i)
        assert fired == [3]
        assert inj.injected_total["io.read"] == 1

    def test_schedule_range_and_multiple_points(self):
        inj = FaultInjector()
        inj.arm(schedule="device.op:2:3, io.write:1")
        dev = []
        for i in range(1, 7):
            try:
                inj.maybe_raise("device.op")
            except InjectedFault:
                dev.append(i)
        assert dev == [2, 3, 4]
        with pytest.raises(InjectedFault):
            inj.maybe_raise("io.write")
        inj.maybe_raise("io.write")  # only the 1st fires

    def test_unknown_point_rejected(self):
        inj = FaultInjector()
        with pytest.raises(ValueError, match="unknown injection point"):
            inj.arm(schedule="io.reed:1")
        with pytest.raises(ValueError):
            inj.arm(rate=0.1, points="nope")

    def test_rate_seeded_reproducible(self):
        def pattern():
            inj = FaultInjector()
            inj.arm(rate=0.5, seed=42)
            out = []
            for _ in range(32):
                try:
                    inj.maybe_raise("io.read")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        p1, p2 = pattern(), pattern()
        assert p1 == p2
        assert 0 < sum(p1) < 32

    def test_rate_restricted_to_points(self):
        inj = FaultInjector()
        inj.arm(rate=0.999999, points="io.read", seed=1)
        with pytest.raises(InjectedFault):
            inj.maybe_raise("io.read")
        inj.maybe_raise("device.op")  # not selected: never fires

    def test_rearm_clears(self):
        inj = FaultInjector()
        inj.arm(schedule="io.read:1")
        inj.arm()  # the no-injection conf of the next query clears
        inj.maybe_raise("io.read")
        assert not inj.armed()


# ---------------------------------------------------------------------------
# Retry driver: backoff, budget, typed exhaustion.
# ---------------------------------------------------------------------------

class TestTransientRetry:
    def conf(self, **kv):
        return TpuConf({**FAST_BACKOFF, **kv})

    def test_recovers_and_accounts(self):
        conf = self.conf()
        INJECTOR.arm(schedule="io.read:1:2")
        s0 = QueryStats.get().snapshot()
        calls = []
        with budget_scope(conf) as budget:
            out = transient_retry(conf, "io.read",
                                  lambda: calls.append(1) or "v")
        assert out == "v" and len(calls) == 1  # 2 injected, 1 real call
        d = QueryStats.delta_since(s0)
        assert d["transient_retries"] == 2
        assert d["faults_injected"] == 2
        assert d["retry_backoff_s"] > 0
        assert [r.attempt for r in budget.history] == [1, 2]
        assert all(r.point == "io.read" for r in budget.history)
        INJECTOR.arm()

    def test_backoff_grows_exponentially(self):
        conf = self.conf(**{
            "spark.rapids.tpu.faults.backoff.baseMs": 2.0,
            "spark.rapids.tpu.faults.backoff.maxMs": 1000.0,
            "spark.rapids.tpu.faults.backoff.multiplier": 4.0})
        INJECTOR.arm(schedule="io.read:1:3", seed=9)
        with budget_scope(conf) as budget:
            transient_retry(conf, "io.read", lambda: None)
        INJECTOR.arm()
        b = [r.backoff_s for r in budget.history]
        assert len(b) == 3
        # jitter is in [0.5, 1.0]: attempt N+1's floor beats attempt N's
        # ceiling at multiplier 4
        assert b[1] > b[0] and b[2] > b[1]

    def test_max_retries_exhaustion(self):
        conf = self.conf(**{"spark.rapids.tpu.faults.maxRetries": 2})
        INJECTOR.arm(schedule="io.read:1:99")
        with budget_scope(conf):
            with pytest.raises(QueryFaulted) as ei:
                transient_retry(conf, "io.read", lambda: None)
        INJECTOR.arm()
        assert ei.value.point == "io.read"
        assert len(ei.value.history) == 3  # 2 retries + the terminal fault

    def test_budget_exhaustion(self):
        conf = self.conf(**{"spark.rapids.tpu.faults.retryBudget": 0})
        INJECTOR.arm(schedule="io.read:1")
        with budget_scope(conf):
            with pytest.raises(QueryFaulted):
                transient_retry(conf, "io.read", lambda: None)
        INJECTOR.arm()

    def test_recovery_disabled_fails_fast(self):
        conf = self.conf(**{
            "spark.rapids.tpu.faults.recovery.enabled": False})
        INJECTOR.arm(schedule="io.read:1")
        with pytest.raises(QueryFaulted):
            transient_retry(conf, "io.read", lambda: None)
        INJECTOR.arm()

    def test_non_retryable_passthrough(self):
        def missing():
            raise FileNotFoundError("gone")

        with pytest.raises(FileNotFoundError):
            transient_retry(self.conf(), "io.read", missing)

    def test_real_transient_oserror_retried(self):
        conf = self.conf()
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise OSError("EIO: device hiccup")
            return state["n"]

        assert transient_retry(conf, "io.read", flaky) == 2

    def test_io_write_only_injected_retry(self):
        """A real write error is NOT retried in place (it could
        duplicate rows mid-stream); only injected faults are."""
        def bad_write():
            raise OSError("disk full")

        with pytest.raises(OSError):
            transient_retry(self.conf(), "io.write", bad_write)


# ---------------------------------------------------------------------------
# io.read through a real scan.
# ---------------------------------------------------------------------------

class TestIoRead:
    def test_fault_recovers_query(self, faults_session, tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame())
        clean = _agg_rows(s, path)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule", "io.read:1")
        before = QueryStats.get().snapshot()
        assert _agg_rows(s, path) == clean
        d = QueryStats.delta_since(before)
        assert d["faults_injected"] >= 1
        assert d["transient_retries"] >= 1
        get_catalog().assert_no_leaks()

    def test_fault_without_recovery_is_typed(self, faults_session,
                                             tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame())
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "io.read:1:999")
        s.conf.set("spark.rapids.tpu.faults.recovery.enabled", False)
        with pytest.raises(QueryFaulted) as ei:
            _agg_rows(s, path)
        assert ei.value.point == "io.read"
        assert ei.value.history  # fault history rides the exception
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# device.op: bounded re-dispatch, then CPU degradation.
# ---------------------------------------------------------------------------

class TestDeviceOp:
    def test_fault_retries_then_succeeds(self, faults_session, tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame())
        clean = _agg_rows(s, path)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "device.op:1")
        before = QueryStats.get().snapshot()
        assert _agg_rows(s, path) == clean
        d = QueryStats.delta_since(before)
        assert d["transient_retries"] >= 1
        assert d["degraded_batches"] == 0

    def test_repeated_fault_degrades_to_cpu(self, faults_session,
                                            tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=1500))
        clean = _agg_rows(s, path)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "device.op:1:9")
        s.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
        before = QueryStats.get().snapshot()
        assert _agg_rows(s, path) == clean
        d = QueryStats.delta_since(before)
        assert d["degraded_batches"] >= 1
        tr = s.last_trace()
        assert tr is not None and tr.status == "degraded"
        marks = [e[1] for e in tr.events]
        assert "degraded:cpu" in marks
        get_catalog().assert_no_leaks()

    def test_degrade_disabled_faults_typed(self, faults_session, tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=800))
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "device.op:1:99")
        s.conf.set("spark.rapids.tpu.faults.degrade.enabled", False)
        with pytest.raises(QueryFaulted) as ei:
            _agg_rows(s, path)
        assert ei.value.point == "device.op"
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# shuffle.fragment: recompute from the producing stage's durable output.
# ---------------------------------------------------------------------------

class TestShuffleFragment:
    def test_host_shuffle_unit_injection(self, tmp_path):
        from spark_rapids_tpu.parallel.host_shuffle import HostShuffle
        conf = TpuConf(FAST_BACKOFF)
        sh = HostShuffle(2, str(tmp_path), num_threads=1)
        try:
            sh.write_partition(0, pa.table({"x": [1, 2, 3]}))
            sh.write_partition(0, pa.table({"x": [4]}))
            sh.finish_writes()
            INJECTOR.arm(schedule="shuffle.fragment:1")
            s0 = QueryStats.get().snapshot()
            tables = transient_retry(
                conf, "shuffle.fragment",
                lambda: list(sh.read_partition(0)),
                recover_counter="fragments_recomputed")
            assert sum(t.num_rows for t in tables) == 4
            d = QueryStats.delta_since(s0)
            assert d["fragments_recomputed"] == 1
            assert d["transient_retries"] == 1
        finally:
            INJECTOR.arm()
            sh.close()

    def test_exchange_fragment_recovers_query(self, faults_session, rng):
        s = faults_session
        pdf = _frame(n=2500, seed=5)
        table = pa.Table.from_pandas(pdf, preserve_index=False)
        s.conf.set("spark.rapids.tpu.shuffle.mode", "HOST")
        df = s.create_dataframe(table)

        def run():
            return sorted(df.group_by("k").agg(
                F.sum(F.col("a")).alias("s")).collect())

        clean = run()
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "shuffle.fragment:1")
        before = QueryStats.get().snapshot()
        assert run() == clean
        d = QueryStats.delta_since(before)
        assert d["faults_injected"] >= 1
        assert d["fragments_recomputed"] >= 1
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# cache.lookup: degrade to miss, never a poisoned entry.
# ---------------------------------------------------------------------------

class TestCacheLookup:
    def test_fault_degrades_to_miss_then_hits(self, faults_session,
                                              tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame())
        s.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
        clear_query_cache()
        clean = _agg_rows(s, path)  # populates the cache
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "cache.lookup:1")
        before = QueryStats.get().snapshot()
        assert _agg_rows(s, path) == clean  # faulted lookup -> recompute
        d = QueryStats.delta_since(before)
        assert d["cache_misses"] >= 1
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        before = QueryStats.get().snapshot()
        assert _agg_rows(s, path) == clean
        assert QueryStats.delta_since(before)["cache_hits"] >= 1
        clear_query_cache()
        get_catalog().assert_no_leaks()

    def test_faulted_fill_leaves_no_poisoned_entry(self, faults_session,
                                                   tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=900, seed=2))
        s.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
        clear_query_cache()
        # invocation 1 = the lookup (miss, clean); invocation 2 = the
        # first fill registration -> the fill is abandoned, not poisoned
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "cache.lookup:2")
        clean = _agg_rows(s, path)
        cache = get_query_cache()
        assert cache.entry_count() == 0  # abandoned fill indexed nothing
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        assert _agg_rows(s, path) == clean  # clean populate
        assert cache.entry_count() >= 1
        assert _agg_rows(s, path) == clean  # served from cache
        clear_query_cache()
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# io.write: atomic temp+rename; injected faults retry, aborts clean up.
# ---------------------------------------------------------------------------

class TestIoWrite:
    def test_injected_fault_retries_write(self, faults_session, tmp_path):
        s = faults_session
        src = _write_pq(tmp_path, "src.parquet", _frame(n=600, seed=3))
        out = str(tmp_path / "out")
        s.conf.set("spark.rapids.tpu.faults.inject.schedule", "io.write:1")
        before = QueryStats.get().snapshot()
        stats = s.read_parquet(src).write.mode("overwrite").parquet(out)
        assert QueryStats.delta_since(before)["transient_retries"] >= 1
        assert stats.num_rows == 600
        files = os.listdir(out)
        assert files and not [f for f in files if "inprogress" in f]
        back = pq.read_table(out).to_pandas().sort_values("a")
        assert len(back) == 600

    def test_abort_leaves_no_partial_file(self, faults_session, tmp_path):
        s = faults_session
        src = _write_pq(tmp_path, "src.parquet", _frame(n=600, seed=4))
        out = str(tmp_path / "out_fail")
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "io.write:1:999")
        s.conf.set("spark.rapids.tpu.faults.recovery.enabled", False)
        with pytest.raises(QueryFaulted) as ei:
            s.read_parquet(src).write.mode("overwrite").parquet(out)
        assert ei.value.point == "io.write"
        # an injected mid-write fault never leaves a partial file
        # visible: the temp was deleted, nothing was renamed into place
        leftovers = [f for f in os.listdir(out)] if os.path.exists(out) \
            else []
        assert not [f for f in leftovers if f.endswith(".parquet")]
        assert not [f for f in leftovers if "inprogress" in f]
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# dcn.heartbeat: connect + heartbeat retries via the framework.
# ---------------------------------------------------------------------------

class TestDcnHeartbeat:
    def test_connect_retries_injected_fault(self):
        from spark_rapids_tpu.parallel.dcn import Coordinator, ProcessGroup
        for k, v in FAST_BACKOFF.items():
            TpuConf.set_session(k, v)
        coord = Coordinator(1)
        try:
            INJECTOR.arm(schedule="dcn.heartbeat:1")
            s0 = QueryStats.get().snapshot()
            pg = ProcessGroup(0, 1, ("127.0.0.1", coord.port),
                              coordinator=coord)
            assert QueryStats.delta_since(s0)["transient_retries"] >= 1
            pg.close()
        finally:
            INJECTOR.arm()
            coord.close()
            for k in FAST_BACKOFF:
                TpuConf.unset_session(k)

    def test_connect_faults_typed_without_recovery(self):
        from spark_rapids_tpu.parallel.dcn import Coordinator, ProcessGroup
        TpuConf.set_session("spark.rapids.tpu.faults.recovery.enabled",
                            False)
        coord = Coordinator(1)
        try:
            INJECTOR.arm(schedule="dcn.heartbeat:1:999")
            with pytest.raises(QueryFaulted) as ei:
                ProcessGroup(0, 1, ("127.0.0.1", coord.port),
                             coordinator=coord)
            assert ei.value.point == "dcn.heartbeat"
        finally:
            INJECTOR.arm()
            coord.close()
            TpuConf.unset_session("spark.rapids.tpu.faults.recovery.enabled")
        get_catalog().assert_no_leaks()

    def test_peer_failed_error_is_transient(self):
        from spark_rapids_tpu.parallel.dcn import PeerFailedError
        assert issubclass(PeerFailedError, TransientFault)


# ---------------------------------------------------------------------------
# Leak hygiene: one persistent fault at every in-query injection point,
# recovery disabled -> typed QueryFaulted, permits released, no leaked
# handles, and a FINISHED trace carrying the 'faulted' status.
# ---------------------------------------------------------------------------

IN_QUERY_POINTS = [
    ("io.read", {}),
    ("device.op", {"spark.rapids.tpu.faults.degrade.enabled": False}),
    ("shuffle.fragment", {"spark.rapids.tpu.shuffle.mode": "HOST"}),
    ("cache.lookup", {"spark.rapids.tpu.sql.cache.enabled": True}),
]


class TestLeakHygiene:
    @pytest.mark.parametrize("point,extra",
                             IN_QUERY_POINTS, ids=[p for p, _ in
                                                   IN_QUERY_POINTS])
    def test_faulted_query_releases_everything(self, faults_session,
                                               tmp_path, point, extra):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=1200, seed=8))
        for k, v in extra.items():
            s.conf.set(k, v)
        clear_query_cache()
        s.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
        s.conf.set("spark.rapids.tpu.faults.recovery.enabled", False)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   f"{point}:1:9999")
        sched = s.scheduler()
        handle = s.submit(
            lambda: _agg_rows(s, path), label=f"faulted-{point}")
        with pytest.raises(QueryFaulted) as ei:
            handle.result(timeout=120)
        assert ei.value.point == point
        assert handle.status == "faulted"
        assert sched.running() == 0  # permit + slot released
        tr = handle.trace()
        assert tr is not None and tr.t_end is not None
        assert tr.status == "faulted"  # the trace FINISHED, accurately
        clear_query_cache()
        get_catalog().assert_no_leaks()
        # the released permit admits the next (clean) query
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        s.conf.unset("spark.rapids.tpu.faults.recovery.enabled")
        assert len(_agg_rows(s, path)) > 0
        for k in extra:
            s.conf.unset(k)
        clear_query_cache()
        get_catalog().assert_no_leaks()

    def test_faulted_write_releases_everything(self, faults_session,
                                               tmp_path):
        s = faults_session
        src = _write_pq(tmp_path, "src.parquet", _frame(n=500, seed=9))
        out = str(tmp_path / "w")
        s.conf.set("spark.rapids.tpu.faults.recovery.enabled", False)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "io.write:1:9999")
        handle = s.submit(lambda: s.read_parquet(src).write
                          .mode("overwrite").parquet(out),
                          label="faulted-write")
        with pytest.raises(QueryFaulted):
            handle.result(timeout=120)
        assert handle.status == "faulted"
        assert s.scheduler().running() == 0
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# Gray-point leak hygiene: the five gray injectors (corruption x3, hang,
# slow peer) each drive their full detection+recovery path with every
# handle released — corruption either heals (drop-and-miss / re-pull) or
# fails typed+resubmittable; a hang is reclaimed by the watchdog; a slow
# peer is answered late, never hung on.
# ---------------------------------------------------------------------------

GRAY_POINTS = ["shuffle.corrupt", "spill.corrupt", "cache.corrupt",
               "device.hang", "dcn.slow_peer"]


class TestGrayLeakHygiene:
    @pytest.mark.parametrize("point", GRAY_POINTS)
    def test_gray_point_releases_everything(self, faults_session,
                                            tmp_path, point):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=1500, seed=17))
        clear_query_cache()
        clean = _agg_rows(s, path)
        before = QueryStats.get().snapshot()
        if point == "shuffle.corrupt":
            # persistent corruption + recovery disabled: the very first
            # integrity failure surfaces typed through the scheduler
            s.conf.set("spark.rapids.tpu.shuffle.mode", "HOST")
            s.conf.set("spark.rapids.tpu.faults.recovery.enabled", False)
            s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                       f"{point}:1:9999")
            handle = s.submit(lambda: _agg_rows(s, path),
                              label=f"gray-{point}")
            with pytest.raises(QueryFaulted) as ei:
                handle.result(timeout=120)
            assert ei.value.point == "shuffle.fragment"
            assert handle.status == "faulted"
            assert s.scheduler().running() == 0
            d = QueryStats.delta_since(before)
            assert d["integrity_failures"] >= 1
        elif point == "spill.corrupt":
            # a corrupted spill file backing live state: typed AND
            # resubmittable; the handle still closes clean
            import jax.numpy as jnp

            from spark_rapids_tpu import types as T
            from spark_rapids_tpu.batch import (ColumnBatch, DeviceColumn,
                                                Field, Schema)
            cat = get_catalog()
            h = cat.register(ColumnBatch(
                Schema([Field("x", T.INT64, False)]),
                [DeviceColumn(T.INT64, jnp.arange(16))], 16))
            h.spill_to_host()
            h.spill_to_disk()
            INJECTOR.arm(schedule=f"{point}:1:9999")
            with pytest.raises(QueryFaulted) as ei:
                h.get()
            assert ei.value.resubmittable
            INJECTOR.arm()
            h.close()
            assert QueryStats.delta_since(before)[
                "integrity_failures"] >= 1
        elif point == "cache.corrupt":
            # a corrupt cache entry NEVER fails the query: the lookup
            # drops it and serves a miss; results stay identical even
            # under persistent corruption
            s.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
            clear_query_cache()
            assert _agg_rows(s, path) == clean  # populate
            s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                       f"{point}:1:9999")
            assert _agg_rows(s, path) == clean  # drop-and-miss
            d = QueryStats.delta_since(before)
            assert d["integrity_failures"] >= 1
            assert d["cache_misses"] >= 1
            s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
            clear_query_cache()
        elif point == "device.hang":
            # a wedged dispatch: the watchdog reclaims the query — typed
            # faulted(resubmittable), permit released, trace FINISHED
            s.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
            s.conf.set("spark.rapids.tpu.faults.watchdog.stallMs", 250.0)
            s.conf.set("spark.rapids.tpu.faults.resubmit.max", 0)
            s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                       f"{point}:1")
            handle = s.submit(lambda: _agg_rows(s, path),
                              label=f"gray-{point}")
            with pytest.raises(QueryFaulted) as ei:
                handle.result(timeout=60)
            assert ei.value.resubmittable
            assert handle.status == "faulted"
            assert s.scheduler().running() == 0
            tr = handle.trace()
            assert tr is not None and tr.t_end is not None
            assert tr.status == "faulted"
            assert "watchdog:stall" in [e[1] for e in tr.events]
        else:  # dcn.slow_peer
            # a straggling peer server answers late; the fetch still
            # completes and nothing hangs or leaks
            from spark_rapids_tpu.config import TpuConf
            from spark_rapids_tpu.parallel.dcn import (Coordinator,
                                                       DcnShuffle,
                                                       ProcessGroup)
            TpuConf.set_session(
                "spark.rapids.tpu.faults.hedge.quantileMs", 40.0)
            coord = Coordinator(1)
            try:
                pg = ProcessGroup(0, 1, ("127.0.0.1", coord.port),
                                  coordinator=coord)
                sh = DcnShuffle(pg, 1, str(tmp_path / "slowpeer"))
                sh.write_partition(0, pa.table({"x": [1, 2, 3]}))
                sh.local.finish_writes()
                INJECTOR.arm(schedule=f"{point}:1")
                assert pg.fetch(0, sh.id, 0)
                INJECTOR.arm()
                pg.unregister_shuffle(sh.id)
                sh.local.close()
                pg.close()
            finally:
                INJECTOR.arm()
                coord.close()
                TpuConf.unset_session(
                    "spark.rapids.tpu.faults.hedge.quantileMs")
        # common epilogue: a clean query still runs, nothing leaked
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        s.conf.unset("spark.rapids.tpu.faults.recovery.enabled")
        s.conf.unset("spark.rapids.tpu.shuffle.mode")
        assert _agg_rows(s, path) == clean
        clear_query_cache()
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# Chaos differential (the acceptance gate): >=1 fault at EVERY registered
# injection point — fail-stop AND gray — under a seeded schedule; results
# identical to the fault-free run; zero leaked handles; accurate trace
# statuses.
# ---------------------------------------------------------------------------

class TestChaosDifferential:
    def test_seeded_schedule_differential(self, faults_session, tmp_path):
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=4000, seed=13))
        out = str(tmp_path / "chaos_out")
        s.conf.set("spark.rapids.tpu.sql.cache.enabled", True)
        s.conf.set("spark.rapids.tpu.shuffle.mode", "HOST")
        s.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
        clear_query_cache()

        def run_all():
            rows = _agg_rows(s, path)
            res = s.read_parquet(path).filter(F.col("b") < 0.7)
            res.write.mode("overwrite").parquet(out)
            back = sorted(pq.read_table(out).to_pandas()["a"].tolist())
            return rows, back

        clean_rows, clean_back = run_all()
        INJECTOR.reset_totals()
        before = QueryStats.get().snapshot()
        # fail-stop AND gray in one schedule: shuffle.corrupt flips a
        # bit in a host-shuffle frame (integrity verify -> re-pull heals
        # it inside the same query)
        s.conf.set(
            "spark.rapids.tpu.faults.inject.schedule",
            "io.read:1,device.op:1,cache.lookup:1,"
            "shuffle.fragment:1,io.write:1,shuffle.corrupt:1")
        s.conf.set("spark.rapids.tpu.faults.inject.seed", 7)
        faulted_rows, faulted_back = run_all()
        # identical results under faults
        assert faulted_rows == clean_rows
        assert faulted_back == clean_back

        # cache.corrupt leg: its own schedule (cache.lookup:1 above
        # degrades every query's FIRST lookup to a miss before the
        # entry is ever found, so the corrupt check needs a clean
        # lookup): the poisoned entry is dropped, the query recomputes
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "cache.corrupt:1")
        assert _agg_rows(s, path) == clean_rows
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        # every recovered query's trace finished with an accurate status
        # (checked before the hang leg below, whose trace is accurately
        # 'faulted')
        tr = s.last_trace()
        assert tr is not None and tr.status in ("ok", "degraded")

        # device.hang leg: a wedged dispatch is reclaimed by the
        # watchdog — faulted(resubmittable), permit released
        s.conf.set("spark.rapids.tpu.faults.watchdog.stallMs", 250.0)
        s.conf.set("spark.rapids.tpu.faults.resubmit.max", 0)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "device.hang:1")
        handle = s.submit(lambda: _agg_rows(s, path), label="chaos-hang")
        with pytest.raises(QueryFaulted) as ei:
            handle.result(timeout=60)
        assert ei.value.resubmittable
        assert s.scheduler().running() == 0
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        s.conf.unset("spark.rapids.tpu.faults.watchdog.stallMs")
        s.conf.unset("spark.rapids.tpu.faults.resubmit.max")

        # spill.corrupt leg: a corrupted spill file backing live state
        # fails typed + resubmittable (no durable copy at this placement)
        import jax.numpy as jnp

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.batch import (ColumnBatch, DeviceColumn,
                                            Field, Schema)
        cat = get_catalog()
        h = cat.register(ColumnBatch(
            Schema([Field("x", T.INT64, False)]),
            [DeviceColumn(T.INT64, jnp.arange(8))], 8))
        h.spill_to_host()
        h.spill_to_disk()
        INJECTOR.arm(schedule="spill.corrupt:1")
        with pytest.raises(QueryFaulted) as ei:
            h.get()
        assert ei.value.resubmittable
        INJECTOR.arm()
        h.close()

        # the dcn legs of the schedule: a mini process group riding the
        # same injection points (no ExecContext re-arms here).
        # dcn.heartbeat exercises the transient connect retry;
        # dcn.slow_peer delays a peer-server fetch reply (slow, not
        # dead); dcn.peer_kill kills the rank (silent mode: heartbeats
        # stop, peer server freezes, the rank's own query unwinds typed)
        s.conf.set("spark.rapids.tpu.faults.hedge.quantileMs", 40.0)
        from spark_rapids_tpu.parallel.dcn import (Coordinator, DcnShuffle,
                                                   PeerLostError,
                                                   ProcessGroup)
        INJECTOR.arm(schedule="dcn.heartbeat:1")
        coord = Coordinator(1)
        try:
            pg = ProcessGroup(0, 1, ("127.0.0.1", coord.port),
                              coordinator=coord)
            pg.barrier()
            sh = DcnShuffle(pg, 1, str(tmp_path / "dcn_chaos"))
            sh.write_partition(0, pa.table({"x": [1, 2]}))
            sh.local.finish_writes()
            INJECTOR.arm(schedule="dcn.slow_peer:1")
            assert pg.fetch(0, sh.id, 0)  # answered, just late
            pg.unregister_shuffle(sh.id)
            sh.local.close()
            INJECTOR.arm(schedule="dcn.peer_kill:1")
            with pytest.raises(PeerLostError, match="killed"):
                pg.note_op()
            pg.close()
        finally:
            INJECTOR.arm()
            coord.close()
        # dcn.coordinator_kill: the hosting rank's note_op kills the
        # coordinator with the rank (silent mode: both freeze; the
        # rank's own query unwinds typed — failover is the SURVIVORS'
        # story, covered by tests/test_dcn_failures.py)
        INJECTOR.arm(schedule="dcn.coordinator_kill:1")
        coord2 = Coordinator(1)
        try:
            pg2 = ProcessGroup(0, 1, ("127.0.0.1", coord2.port),
                               coordinator=coord2)
            with pytest.raises(PeerLostError, match="coordinator"):
                pg2.note_op()
            pg2.close()
        finally:
            INJECTOR.arm()
            coord2.close()
        # the NETWORK legs (dcn.partition / dcn.net.dup /
        # dcn.net.reorder) need a REAL link, so a world=2 mini group:
        # rank 1's frames to the rank-0 coordinator ride the fabric.
        # A dropped control frame recovers by re-dialing the SAME
        # coordinator (no failover, no election); duplicated and
        # stale-reordered deliveries replay byte-identically from the
        # dedup journal (frames_deduped) instead of re-applying.
        import threading as _th

        from spark_rapids_tpu.utils.metrics import QueryStats as _QS
        coord3 = Coordinator(2, heartbeat_timeout=30.0)
        pgs3 = [None, None]

        def _mk(r):
            pgs3[r] = ProcessGroup(
                r, 2, ("127.0.0.1", coord3.port),
                coordinator=coord3 if r == 0 else None,
                heartbeat_interval=60.0)

        ts3 = [_th.Thread(target=_mk, args=(r,)) for r in range(2)]
        for t in ts3:
            t.start()
        for t in ts3:
            t.join(timeout=30)
        try:
            assert pgs3[0] is not None and pgs3[1] is not None
            INJECTOR.arm(schedule="dcn.partition:1")
            msg, _ = pgs3[1]._request({"op": "members"})
            assert 1 in [int(r) for r in msg["peers"]]
            assert INJECTOR.snapshot()[
                "injected_total"]["dcn.partition"] >= 1
            dedup_before = _QS.process().frames_deduped
            INJECTOR.arm(schedule="dcn.net.dup:1")
            msg, _ = pgs3[1]._request({"op": "members"})
            assert "epoch" in msg
            INJECTOR.arm(schedule="dcn.net.reorder:1")
            msg, _ = pgs3[1]._request({"op": "members"})
            assert "epoch" in msg
            INJECTOR.arm()
            assert _QS.process().frames_deduped > dedup_before
        finally:
            INJECTOR.arm()
            for pg3 in pgs3:
                if pg3 is not None:
                    pg3.close()
            coord3.close()

        # server.conn leg: the network front door's client drops
        # mid-result-stream (injected at the BATCH send) — the wire
        # query cancels cooperatively, the permit and the wire-query
        # registry entry release, and a fresh connection still serves
        from spark_rapids_tpu.server import SqlFrontDoor, WireClient
        door = SqlFrontDoor(s).start()
        door.register_table(
            "t", lambda: s.read_parquet(path))
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "server.conn:1")
        try:
            c = WireClient("127.0.0.1", door.port)
            with pytest.raises((ConnectionError, OSError)):
                c.query({"table": "t", "ops": []})
            s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and (
                    s.scheduler().running()
                    or door.snapshot()["queries_inflight"]):
                time.sleep(0.05)
            assert s.scheduler().running() == 0
            assert door.snapshot()["queries_inflight"] == 0
            with WireClient("127.0.0.1", door.port) as c2:
                assert c2.query({"table": "t", "ops": []}).rows()
        finally:
            door.close()
            s.conf.unset("spark.rapids.tpu.faults.inject.schedule")

        # server.malformed leg: a frame synthetically corrupt on
        # arrival (injected at the recv path AFTER a clean decode, so
        # the REAL strike machinery is the recovery path) — typed
        # BAD_REQUEST with a strike, the connection survives, and the
        # SAME connection then serves the exact rows
        from spark_rapids_tpu.server import WireError
        door2 = SqlFrontDoor(s).start()
        door2.register_table("t", lambda: s.read_parquet(path))
        try:
            # connect BEFORE arming: the HELLO flows through the same
            # injection point and must not eat the scheduled firing
            c = WireClient("127.0.0.1", door2.port)
            INJECTOR.arm(schedule="server.malformed:1")
            with pytest.raises(WireError) as ei:
                c.query({"table": "t", "ops": []})
            assert ei.value.code == "BAD_REQUEST"
            assert ei.value.reason == "malformed"
            assert "strike 1/" in (ei.value.detail or "")
            INJECTOR.arm()
            assert c.query({"table": "t", "ops": []}).rows()
            c.close()
            assert door2.snapshot()["queries_inflight"] == 0
            assert door2.snapshot()["decode_errors"] >= 1
        finally:
            INJECTOR.arm()
            door2.close()

        # >=1 injected fault at EVERY registered point
        totals = INJECTOR.snapshot()["injected_total"]
        for p in POINTS:
            assert totals[p] >= 1, f"point {p} never fired: {totals}"
        d = QueryStats.delta_since(before)
        assert d["transient_retries"] >= 4
        assert d["retry_backoff_s"] > 0
        # gray detection is attributable: corruption was CAUGHT, the
        # watchdog saw the hang
        assert d["integrity_failures"] >= 2  # shuffle + cache (+ spill)
        assert d["fragments_recomputed"] >= 1
        # the stall landed on the process aggregate (watchdog thread)
        assert QueryStats.process().stalls_detected >= 1
        # zero spill-handle leaks once the (legitimately long-lived)
        # cache entries are dropped
        clear_query_cache()
        get_catalog().assert_no_leaks()
        sched = getattr(s, "_scheduler", None)
        if sched is not None:
            assert sched.running() == 0

    def test_seeded_rate_chaos(self, faults_session, tmp_path):
        """Probabilistic chaos (the SRT_BENCH_FAULT_RATE shape): a
        seeded rate over every point still yields the fault-free
        answer."""
        s = faults_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=2500, seed=21))
        s.conf.set("spark.rapids.tpu.shuffle.mode", "HOST")
        clean = _agg_rows(s, path)
        s.conf.set("spark.rapids.tpu.faults.inject.rate", 0.15)
        s.conf.set("spark.rapids.tpu.faults.inject.seed", 123)
        # rate mode is a TRUE rate (the injector preserves its RNG
        # stream across identical per-query re-arms), so at 0.15 a call
        # site can draw several consecutive faults; headroom above the
        # default 3 keeps per-site exhaustion odds negligible (0.15^7)
        # while every recovery path still exercises
        s.conf.set("spark.rapids.tpu.faults.maxRetries", 6)
        before = QueryStats.get().snapshot()
        for _ in range(3):
            assert _agg_rows(s, path) == clean
        assert QueryStats.delta_since(before)["faults_injected"] >= 1
        s.conf.unset("spark.rapids.tpu.faults.maxRetries")
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# Lint + conf registration satellites.
# ---------------------------------------------------------------------------

class TestSatellites:
    def test_faults_confs_registered(self):
        for key in ("spark.rapids.tpu.faults.backoff.baseMs",
                    "spark.rapids.tpu.faults.backoff.maxMs",
                    "spark.rapids.tpu.faults.backoff.multiplier",
                    "spark.rapids.tpu.faults.retryBudget",
                    "spark.rapids.tpu.faults.maxRetries",
                    "spark.rapids.tpu.faults.recovery.enabled",
                    "spark.rapids.tpu.faults.inject.schedule",
                    "spark.rapids.tpu.faults.inject.rate"):
            assert key in ALL_ENTRIES
        assert "faults.backoff.baseMs" in TpuConf.help()

    def test_fault_paths_lint(self, tmp_path):
        from tools.srtlint.engine import run as lint_run
        pkg = tmp_path / "spark_rapids_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text(
            "import time\n"
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
            "def r():\n"
            "    while True:\n"
            "        try:\n"
            "            return g()\n"
            "        except OSError:\n"
            "            time.sleep(0.1)\n")
        (pkg / "ok.py").write_text(
            "import time\n"
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # fault-ok (best effort)\n"
            "        pass\n"
            "def r():\n"
            "    while True:\n"
            "        try:\n"
            "            return g()\n"
            "        except OSError:\n"
            "            time.sleep(0.1)  # fault-ok (bootstrap)\n")
        report = lint_run(str(tmp_path), roots=("spark_rapids_tpu",),
                          rules=["fault-paths"])
        files = sorted({f.path for f in report.failing})
        assert files == ["spark_rapids_tpu/bad.py"]
        msgs = sorted(f.message for f in report.failing)
        assert "swallowing" in msgs[0] or "swallowing" in msgs[1]
        assert any("retry" in m for m in msgs)
        assert len(report.suppressed) == 2

    def test_fault_paths_unbounded_wait_rule(self, tmp_path):
        """Rule 3: no-timeout waits/results/recvs are flagged outside
        faults/ and service/; # wait-ok (<reason>) exempts; timeouts
        pass."""
        from tools.srtlint.engine import run as lint_run
        pkg = tmp_path / "spark_rapids_tpu"
        (pkg / "service").mkdir(parents=True)
        (pkg / "bad_wait.py").write_text(
            "def f(cv, fut, sock):\n"
            "    cv.wait()\n"
            "    fut.result()\n"
            "    sock.recv(4096)\n")
        (pkg / "ok_wait.py").write_text(
            "def f(cv, fut, sock):\n"
            "    cv.wait(timeout=1.0)\n"
            "    fut.result(timeout=5)\n"
            "    cv.wait()  # wait-ok (waker wakes this)\n"
            "    sock.recv(4096)  # wait-ok (socket timeout set at connect)\n")
        (pkg / "service" / "waiter.py").write_text(
            "def f(cv):\n"
            "    cv.wait()\n")  # service/ is the waiting layer: exempt
        report = lint_run(str(tmp_path), roots=("spark_rapids_tpu",),
                          rules=["fault-paths"])
        files = sorted({f.path for f in report.failing})
        assert files == ["spark_rapids_tpu/bad_wait.py"]
        assert len(report.failing) == 3
        assert all("unbounded blocking" in f.message
                   for f in report.failing)

    def test_gray_points_registered(self):
        for p in ("shuffle.corrupt", "spill.corrupt", "cache.corrupt",
                  "device.hang", "dcn.slow_peer"):
            assert p in POINTS
        for key in ("spark.rapids.tpu.faults.integrity.enabled",
                    "spark.rapids.tpu.faults.watchdog.enabled",
                    "spark.rapids.tpu.faults.watchdog.stallMs",
                    "spark.rapids.tpu.faults.hedge.enabled",
                    "spark.rapids.tpu.faults.hedge.quantileMs",
                    "spark.rapids.tpu.faults.dcn.gcOrphanFramesMs"):
            assert key in ALL_ENTRIES

    def test_engine_tree_is_lint_clean(self):
        from tools.srtlint import run_for_pytest
        report = run_for_pytest()
        assert [f for f in report.failing
                if f.rule == "fault-paths"] == []

    def test_query_faulted_exported_from_service(self):
        from spark_rapids_tpu.service import QueryFaulted as QF
        assert QF is QueryFaulted

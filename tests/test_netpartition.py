"""Network partition survival (ISSUE 14): the seeded link-fault fabric,
quorum-fenced coordinator failover, delivery dedup, suspicion strikes,
and heal-and-rejoin.

Tier-1 runs the thread-rank simulations every collection: partition the
minority of a world=3/world=5 group mid-run — the majority completes
byte-identically to fault-free (durable re-pull + adoption), the
minority PARKS with a typed :class:`QuorumLostError` instead of
electing a second coordinator, and after ``FABRIC.heal()`` the parked
rank re-registers under flap damping with zero epoch churn beyond the
single rejoin bump.  The @slow leg reruns the same differential over
real processes (tests/dcn_worker.py ``--net-partition``).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.config import ALL_ENTRIES, TpuConf
from spark_rapids_tpu.faults import INJECTOR
from spark_rapids_tpu.faults.netfabric import (FABRIC, LinkPartitionedError,
                                               NetFabric)
from spark_rapids_tpu.parallel.dcn import (Coordinator, DcnShuffle,
                                           ProcessGroup, QuorumLostError)
from spark_rapids_tpu.utils.metrics import QueryStats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST = {
    "spark.rapids.tpu.faults.backoff.baseMs": 1.0,
    "spark.rapids.tpu.faults.backoff.maxMs": 10.0,
    # the PG-side liveness horizon (vote aging, heartbeat-reply recv
    # timeout) rides this conf; the recv timeout floors at 1 s, so
    # votes age "unreachable" ~2 s after a cut
    "spark.rapids.tpu.dcn.heartbeatTimeout": 0.8,
    # ...and the vote-poll window must cover that aging
    "spark.rapids.tpu.dcn.quorum.windowMs": 3500.0,
}


@pytest.fixture()
def net_conf():
    for k, v in FAST.items():
        TpuConf.set_session(k, v)
    yield
    for k in FAST:
        TpuConf.unset_session(k)
    INJECTOR.arm()
    FABRIC.reset()  # clear any standing program, runtime cuts included


def _make_group(world, hb_timeout=0.4, wait_timeout=10.0, interval=0.1):
    coord = Coordinator(world, heartbeat_timeout=hb_timeout,
                        wait_timeout=wait_timeout)
    pgs = [None] * world
    errs = []

    def mk(r):
        try:
            pgs[r] = ProcessGroup(r, world, ("127.0.0.1", coord.port),
                                  coordinator=coord if r == 0 else None,
                                  heartbeat_interval=interval)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    return coord, pgs


def _close_all(pgs):
    for pg in pgs:
        if pg is not None:
            try:
                pg.close()
            except Exception:  # fault-ok (chaos teardown of parked/partitioned ranks)
                pass


def _wait(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(
        f"timed out waiting for {what() if callable(what) else what}")


def _active_coordinators(coord, pgs):
    coords = [coord] + [pg.coordinator for pg in pgs
                        if pg is not None and pg.coordinator is not None
                        and pg.coordinator is not coord]
    return [c for c in coords if c.is_active()]


# ---------------------------------------------------------------------------
# The fabric itself.
# ---------------------------------------------------------------------------

class TestNetFabric:
    def test_partition_grammar(self):
        f = NetFabric()
        f.arm(partition="0>2")
        with pytest.raises(LinkPartitionedError):
            f.check_send(0, 2)
        f.check_send(2, 0)  # asymmetric: reverse direction flows
        f.arm(partition="1-2")
        with pytest.raises(LinkPartitionedError):
            f.check_send(1, 2)
        with pytest.raises(LinkPartitionedError):
            f.check_send(2, 1)
        f.arm(partition="0+1|2")
        for s, d in ((0, 2), (2, 0), (1, 2), (2, 1)):
            with pytest.raises(LinkPartitionedError):
                f.check_send(s, d)
        f.check_send(0, 1)
        f.arm(partition="2|*")
        with pytest.raises(LinkPartitionedError):
            f.check_send(2, 4)
        with pytest.raises(LinkPartitionedError):
            f.check_send(3, 2)
        f.check_send(0, 1)
        with pytest.raises(ValueError):
            f.arm(partition="bogus")

    def test_loopback_never_faulted(self):
        f = NetFabric()
        f.arm(partition="*|*", delay="*:5000")
        f.check_send(1, 1)  # a rank's own link is exempt

    def test_delay_applies(self):
        f = NetFabric()
        f.arm(delay="0>1:80")
        t0 = time.monotonic()
        f.check_send(0, 1)
        assert time.monotonic() - t0 >= 0.07
        t0 = time.monotonic()
        f.check_send(1, 0)  # one-way: reverse is instant
        assert time.monotonic() - t0 < 0.05

    def test_after_ops_gates_engagement(self):
        f = NetFabric()
        f.arm(partition="0-1", after_ops=2)
        f.check_send(0, 1)  # not engaged yet
        f.note_op()
        f.check_send(0, 1)
        f.note_op()
        with pytest.raises(LinkPartitionedError):
            f.check_send(0, 1)

    def test_heal_is_sticky_across_identical_rearm(self):
        f = NetFabric()
        f.arm(partition="0-1")
        with pytest.raises(LinkPartitionedError):
            f.check_send(0, 1)
        f.heal()
        f.check_send(0, 1)
        f.arm(partition="0-1")  # identical re-arm (next ExecContext)
        f.check_send(0, 1)  # still healed
        f.arm(partition="0-2")  # CHANGED program re-engages
        with pytest.raises(LinkPartitionedError):
            f.check_send(0, 2)

    def test_seeded_dup_reorder_deterministic(self):
        msgs = [({"op": "x", "n": i}, b"") for i in range(40)]

        def run():
            f = NetFabric()
            f.arm(dup_rate=0.3, reorder_rate=0.3, seed=7)
            out = []
            prev = None
            for m, b in msgs:
                ds = f.deliveries(0, 1, m, b, prev=prev)
                out.append(tuple(d[0]["n"] for d in ds))
                prev = (m, b)
            return out, f.frames_duplicated, f.frames_reordered

        a, b = run(), run()
        assert a == b
        assert a[1] > 0 and a[2] > 0
        # exactly one reply per received frame, always the current one
        f = NetFabric()
        f.arm(dup_rate=1.0)
        ds = f.deliveries(0, 1, {"op": "y"}, b"")
        assert [d[2] for d in ds] == [False, True]

    def test_confs_registered(self):
        for key in ("spark.rapids.tpu.faults.net.partition",
                    "spark.rapids.tpu.faults.net.delayMs",
                    "spark.rapids.tpu.faults.net.dup.rate",
                    "spark.rapids.tpu.faults.net.reorder.rate",
                    "spark.rapids.tpu.faults.net.seed",
                    "spark.rapids.tpu.faults.net.afterOps",
                    "spark.rapids.tpu.dcn.suspect.strikes",
                    "spark.rapids.tpu.dcn.quorum.enabled",
                    "spark.rapids.tpu.dcn.quorum.windowMs"):
            assert key in ALL_ENTRIES
        from spark_rapids_tpu.faults.injector import POINTS
        for p in ("dcn.partition", "dcn.net.dup", "dcn.net.reorder"):
            assert p in POINTS
        from spark_rapids_tpu.parallel.dcn import DCN_OPS
        assert "vote" in DCN_OPS


# ---------------------------------------------------------------------------
# Suspicion strikes: delay is not death.
# ---------------------------------------------------------------------------

class TestSuspicionStrikes:
    def test_suspected_before_declared(self, net_conf):
        TpuConf.set_session("spark.rapids.tpu.dcn.suspect.strikes", 4)
        try:
            coord, pgs = _make_group(2, hb_timeout=0.3)
            try:
                pgs[1]._closed = True
                pgs[1]._server.freeze()
                _wait(lambda: 1 in coord.suspected(), timeout=5,
                      what="suspicion")
                # suspected is NOT declared: no epoch bump yet
                assert coord.declared_dead() == []
                assert coord.epoch == 0
                _wait(lambda: coord.declared_dead() == [1], timeout=10,
                      what="declaration after strikes")
                assert coord.epoch >= 1
            finally:
                _close_all(pgs)
        finally:
            TpuConf.unset_session("spark.rapids.tpu.dcn.suspect.strikes")

    def test_delay_under_strike_horizon_not_declared(self, net_conf):
        """Injected link delay below strikes x hb_timeout must cause
        suspicion at most — never a death declaration (the satellite's
        whole point: congestion is not death)."""
        coord, pgs = _make_group(2, hb_timeout=0.4, interval=0.1)
        try:
            FABRIC.arm(delay="1>0:250")
            time.sleep(2.5)  # many delayed heartbeat cycles
            assert coord.declared_dead() == []
            assert coord.epoch == 0
        finally:
            FABRIC.reset()
            _close_all(pgs)

    def test_contact_clears_suspicion(self, net_conf):
        """Heartbeat gaps of ~1.4 windows: each gap SUSPECTS the rank,
        each arrival clears it — with the default 2 strikes nobody is
        ever declared."""
        coord, pgs = _make_group(2, hb_timeout=0.5, interval=0.7)
        try:
            time.sleep(2.5)
            assert coord.declared_dead() == []
            assert coord.epoch == 0
        finally:
            _close_all(pgs)

    def test_strikes_one_restores_declare_on_first_timeout(self,
                                                           net_conf):
        """The escape hatch: strikes=1 declares on the first missed
        window — the same 1.4-window heartbeat gaps that survive the
        default now get a rank declared."""
        TpuConf.set_session("spark.rapids.tpu.dcn.suspect.strikes", 1)
        try:
            coord, pgs = _make_group(2, hb_timeout=0.5, interval=0.7)
            try:
                _wait(lambda: len(coord.declared_dead()) > 0, timeout=8,
                      what="strikes=1 declaration")
            finally:
                _close_all(pgs)
        finally:
            TpuConf.unset_session("spark.rapids.tpu.dcn.suspect.strikes")


# ---------------------------------------------------------------------------
# Delivery hardening: duplicated/reordered frames are idempotent.
# ---------------------------------------------------------------------------

class TestDeliveryDedup:
    def test_dup_rate_full_group_still_correct(self, net_conf, tmp_path):
        """Every frame delivered twice: collectives, registers and
        fetches all succeed with byte-identical results, replays
        counted in frames_deduped."""
        coord, pgs = _make_group(2, hb_timeout=30.0, interval=60.0)
        try:
            before = QueryStats.process().frames_deduped
            FABRIC.arm(dup_rate=1.0, seed=3)
            outs = [None, None]

            def gather(i):
                outs[i] = pgs[i].all_gather_bytes(
                    f"payload-{i}".encode(), tag="dup-gather")

            ts = [threading.Thread(target=gather, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert outs[0] == outs[1] == [b"payload-0", b"payload-1"]
            # data plane: a duplicated fetch replays its payload
            sh = DcnShuffle(pgs[0], 1, str(tmp_path / "dup"))
            sh.write_partition(0, pa.table({"x": [1, 2, 3]}))
            sh.local.finish_writes()
            payload = pgs[1].fetch(0, sh.id, 0)
            assert payload
            pgs[0].unregister_shuffle(sh.id)
            sh.local.close()
            assert QueryStats.process().frames_deduped > before
        finally:
            FABRIC.reset()
            _close_all(pgs)

    def test_duplicated_register_single_incarnation(self, net_conf):
        """The non-idempotent op: a duplicated re-register must bump
        the incarnation exactly ONCE (and count one flap, not two) —
        the dedup journal replays the second delivery."""
        coord, pgs = _make_group(2, hb_timeout=0.4)
        reborn = None
        try:
            pgs[1]._closed = True
            pgs[1]._server.freeze()
            _wait(lambda: coord.declared_dead() == [1], timeout=10,
                  what="declaration")
            FABRIC.arm(dup_rate=1.0, seed=5)
            reborn = ProcessGroup(1, 2, ("127.0.0.1", coord.port),
                                  heartbeat_interval=60.0)
            assert reborn.inc == 1  # exactly one bump despite the dup
            assert coord._inc[1] == 1
            assert coord.flap_snapshot()["counts"].get(1, 0) <= 1
        finally:
            FABRIC.reset()
            if reborn is not None:
                reborn.close()
            _close_all(pgs)

    def test_reorder_rate_full_group_still_correct(self, net_conf):
        coord, pgs = _make_group(2, hb_timeout=30.0, interval=60.0)
        try:
            FABRIC.arm(reorder_rate=1.0, seed=9)
            for tag in ("ro-1", "ro-2", "ro-3"):
                outs = [None, None]

                def gather(i, tag=tag):
                    outs[i] = pgs[i].all_gather_bytes(
                        f"{tag}-{i}".encode(), tag=tag)

                ts = [threading.Thread(target=gather, args=(i,))
                      for i in range(2)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=30)
                assert outs[0] == outs[1]
                assert outs[0] == [f"{tag}-0".encode(),
                                   f"{tag}-1".encode()]
        finally:
            FABRIC.reset()
            _close_all(pgs)


# ---------------------------------------------------------------------------
# Quorum-fenced failover + heal-and-rejoin (the tentpole's control plane).
# ---------------------------------------------------------------------------

class TestQuorumFencedFailover:
    def test_majority_side_promotes_minority_coordinator_parks(
            self, net_conf):
        """Partition {0(coord)} | {1, 2}: the majority votes the
        coordinator unreachable and promotes rank 1 at generation 2;
        the OLD coordinator loses its quorum and parks (zero epoch
        bumps — no divergent declarations), so its host rank parks
        typed too.  At most one coordinator generation stays active.
        After heal, rank 0 discovers generation 2, its stale
        coordinator ABDICATES, and it rejoins under flap damping."""
        coord, pgs = _make_group(3, hb_timeout=0.6)
        try:
            s0 = QueryStats.process().snapshot()
            FABRIC.cut("0|1+2")
            # majority side: collectives complete after quorum-fenced
            # failover to rank 1
            outs = [None, None, None]

            def gather(i, tag="post-cut"):
                outs[i] = pgs[i].all_gather_map(
                    f"p{i}".encode(), tag=tag, allow_shrunk=True)

            ts = [threading.Thread(target=gather, args=(i,))
                  for i in (1, 2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert outs[1] is not None and outs[2] is not None
            assert outs[1] == outs[2]
            assert sorted(outs[1][0]) == [1, 2]
            assert pgs[1].coord_rank == 1 and pgs[2].coord_rank == 1
            assert pgs[1].coordinator is not None
            assert pgs[1].coordinator.generation == 2
            # the minority coordinator parked: no declarations of 1/2,
            # and its host rank fails typed
            _wait(lambda: coord.quorum_lost, timeout=10,
                  what="old coordinator quorum park")
            assert coord.declared_dead() == []
            with pytest.raises(QuorumLostError):
                pgs[0].barrier(tag="minority-barrier")
            assert pgs[0].quorum_lost
            # THE invariant: at most one ACTIVE coordinator generation
            assert len(_active_coordinators(coord, pgs)) == 1
            assert not coord.is_active()
            epoch_mid = pgs[1].epoch
            d = QueryStats.delta_since(s0)
            assert d["quorum_losses"] >= 1
            assert d["coordinator_failovers"] >= 2

            # HEAL: rank 0 probes, finds gen 2, abdicates its stale
            # coordinator, re-registers (fresh incarnation)
            FABRIC.heal()
            _wait(lambda: not pgs[0].quorum_lost, timeout=60,
                  what=lambda: (
                      f"rank 0 heal + rejoin (pg0: ql="
                      f"{pgs[0].quorum_lost} coord_rank="
                      f"{pgs[0].coord_rank} gen={pgs[0].coord_gen} "
                      f"inc={pgs[0].inc} defer_in="
                      f"{pgs[0]._heal_defer_until - time.monotonic():.1f}"
                      f" fenced={pgs[0].fenced} "
                      f"lost={pgs[0].coordinator_lost}; old coord: "
                      f"abdicated={coord._abdicated} "
                      f"ql={coord.quorum_lost}; new coord flaps="
                      f"{pgs[1].coordinator.flap_snapshot()})"))
            assert pgs[0].coord_rank == 1
            assert pgs[0].coord_gen == 2
            assert coord._abdicated
            assert len(_active_coordinators(coord, pgs)) == 1
            d = QueryStats.delta_since(s0)
            assert d["rank_rejoins"] >= 1
            # zero churn beyond the single rejoin bump
            epoch_after = pgs[0].epoch
            assert epoch_after <= epoch_mid + 1
            time.sleep(1.0)
            assert pgs[1].coordinator.epoch == epoch_after
            # the healed world=3 group completes a collective again
            # (a FRESH tag: the parked-era tag replays from the journal
            # by design)
            outs = [None, None, None]
            ts = [threading.Thread(target=gather, args=(i, "post-heal"))
                  for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert outs[0] == outs[1] == outs[2]
            assert sorted(outs[0][0]) == [0, 1, 2]
        finally:
            FABRIC.reset()
            _close_all(pgs)

    def test_minority_rank_parks_instead_of_promoting(self, net_conf):
        """Partition {0(coord), 1} | {2}: rank 2 cannot gather a
        connectivity quorum (it reaches nobody) — it PARKS typed
        instead of promoting, while the majority simply declares it
        dead and keeps serving under the ORIGINAL coordinator
        generation.  Heal: rank 2 re-registers (one epoch bump, the
        flap-damping contract)."""
        coord, pgs = _make_group(3, hb_timeout=0.5)
        try:
            FABRIC.cut("2|0+1")
            with pytest.raises(QuorumLostError):
                pgs[2].barrier(tag="cut-barrier")
            assert pgs[2].quorum_lost
            assert pgs[2].coordinator is None  # never promoted
            # majority unaffected: same coordinator, generation 1
            _wait(lambda: coord.declared_dead() == [2], timeout=10,
                  what="majority declares rank 2")
            assert not coord.quorum_lost
            assert coord.generation == 1
            assert pgs[0].coord_rank == 0 and pgs[1].coord_rank == 0
            outs = [None, None]

            def gather(i):
                outs[i] = pgs[i].all_gather_map(
                    f"p{i}".encode(), tag="majority-gather",
                    allow_shrunk=True)

            ts = [threading.Thread(target=gather, args=(i,))
                  for i in (0, 1)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert outs[0] == outs[1]
            assert sorted(outs[0][0]) == [0, 1]
            death_epoch = coord.epoch

            FABRIC.heal()
            _wait(lambda: not pgs[2].quorum_lost, timeout=30,
                  what="rank 2 rejoin")
            assert pgs[2].inc == 1  # fresh incarnation
            assert coord.declared_dead() == []
            assert coord.epoch == death_epoch + 1  # exactly one bump
            time.sleep(1.0)
            assert coord.epoch == death_epoch + 1  # ...and it stays
        finally:
            FABRIC.reset()
            _close_all(pgs)

    def test_asymmetric_link_parks_not_promotes(self, net_conf):
        """One-way loss 2->0 only: rank 2's frames to the coordinator
        vanish while every other link flows.  The voters still reach
        the coordinator, so rank 2 gets no quorum — it parks typed;
        the majority declares it (its heartbeats stopped arriving) and
        keeps the original coordinator."""
        coord, pgs = _make_group(3, hb_timeout=0.5)
        try:
            FABRIC.cut("2>0")
            with pytest.raises(QuorumLostError):
                pgs[2].barrier(tag="asym-barrier")
            assert pgs[2].quorum_lost
            assert pgs[2].coordinator is None
            _wait(lambda: coord.declared_dead() == [2], timeout=10,
                  what="declaration of the one-way-cut rank")
            assert coord.generation == 1 and not coord.quorum_lost
            assert pgs[1].coord_rank == 0  # no failover on the majority
            FABRIC.heal()
            _wait(lambda: not pgs[2].quorum_lost, timeout=30,
                  what="asymmetric heal + rejoin")
            assert coord.declared_dead() == []
        finally:
            FABRIC.reset()
            _close_all(pgs)

    def test_quorum_disabled_escape_hatch(self, net_conf):
        """dcn.quorum.enabled=false restores the fail-stop-biased
        behavior: the cut-off rank presumes coordinator death, burns
        its promote window against the (deterministic but unreachable)
        successor, and fails PERMANENT — never the typed quorum park."""
        TpuConf.set_session("spark.rapids.tpu.dcn.quorum.enabled", False)
        try:
            coord, pgs = _make_group(3, hb_timeout=0.5)
            try:
                FABRIC.cut("2|0+1")
                from spark_rapids_tpu.parallel.dcn import \
                    CoordinatorLostError
                with pytest.raises(CoordinatorLostError) as ei:
                    pgs[2].barrier(tag="unfenced-barrier")
                assert not isinstance(ei.value, QuorumLostError)
                assert not pgs[2].quorum_lost
            finally:
                FABRIC.arm()
                _close_all(pgs)
        finally:
            TpuConf.unset_session("spark.rapids.tpu.dcn.quorum.enabled")


# ---------------------------------------------------------------------------
# The tier-1 partition chaos differential (thread ranks, world=3 and 5).
# ---------------------------------------------------------------------------

def _shuffle_rows(world, n_parts, rows_per, pgs, tmp, cut):
    """Write+commit a DcnShuffle on every rank, cut the fabric, reduce
    on the majority; returns (rows_by_rank, parked_errors_by_rank)."""
    shuffles = [DcnShuffle(pg, n_parts, os.path.join(tmp, f"r{pg.rank}"))
                for pg in pgs]
    for rank, sh in enumerate(shuffles):
        for p in range(n_parts):
            sh.write_partition(p, pa.table(
                {"r": [rank] * rows_per, "p": [p] * rows_per,
                 "v": list(range(rows_per))}))
    ts = [threading.Thread(target=sh.commit) for sh in shuffles]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert all(sh.committed == list(range(world)) for sh in shuffles)
    if cut:
        FABRIC.cut(cut)
    rows = {}
    parked = {}

    def reduce_rank(r):
        try:
            n = 0
            for p in shuffles[r].my_parts():
                n += sum(t_.num_rows
                         for t_ in shuffles[r].read_partition(p))
            for p in shuffles[r].adopt_orphans():
                n += sum(t_.num_rows
                         for t_ in shuffles[r].read_partition(p))
            rows[r] = n
            shuffles[r].close()
        except Exception as e:
            parked[r] = e
            shuffles[r].close()

    ts = [threading.Thread(target=reduce_rank, args=(r,))
          for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    return rows, parked


class TestPartitionChaosDifferentialTier1:
    @pytest.mark.parametrize("world,minority,cut", [
        (3, [2], "2|0+1"),
        (5, [3, 4], "3+4|0+1+2"),
    ])
    def test_majority_completes_minority_parks_then_heals(
            self, net_conf, tmp_path, world, minority, cut):
        n_parts, rows_per = 2 * world, 16
        coord, pgs = _make_group(world, hb_timeout=0.5,
                                 wait_timeout=30.0)
        try:
            s0 = QueryStats.process().snapshot()
            rows, parked = _shuffle_rows(world, n_parts, rows_per, pgs,
                                         str(tmp_path), cut)
            majority = [r for r in range(world) if r not in minority]
            # the majority's union covers EVERY rank's committed map
            # output — byte count identical to the fault-free total
            assert sum(rows.get(r, 0) for r in majority) \
                == world * n_parts * rows_per
            # every minority rank parked TYPED (QuorumLostError direct,
            # or wrapped typed by the retry layer) — never wrong rows
            from spark_rapids_tpu.faults.recovery import QueryFaulted
            for r in minority:
                assert r in parked, f"rank {r} did not park: {rows}"
                e = parked[r]
                assert isinstance(e, (QuorumLostError, QueryFaulted)), e
                assert pgs[r].quorum_lost
            assert not coord.quorum_lost
            assert coord.generation == 1  # no election happened
            assert len(_active_coordinators(coord, pgs)) == 1
            d = QueryStats.delta_since(s0)
            assert d["quorum_losses"] >= len(minority)
            death_epoch = coord.epoch

            # HEAL: every parked rank rejoins; zero churn beyond one
            # rejoin bump per rank (the flap-damping contract)
            FABRIC.heal()
            for r in minority:
                _wait(lambda r=r: not pgs[r].quorum_lost, timeout=40,
                      what=f"rank {r} rejoin")
            assert coord.declared_dead() == []
            assert coord.epoch == death_epoch + len(minority)
            time.sleep(1.0)
            assert coord.epoch == death_epoch + len(minority)
            d = QueryStats.delta_since(s0)
            assert d["rank_rejoins"] >= len(minority)
        finally:
            FABRIC.reset()
            _close_all(pgs)


# ---------------------------------------------------------------------------
# Wire satellites: the sibling-sweep demotion and the result-stream
# delivery check at the protocol decoder.
# ---------------------------------------------------------------------------

def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestWireClientSweepDemotion:
    def test_failover_demotes_dark_endpoints(self, session):
        """Under a half-partitioned fleet the GOAWAY sweep must not
        burn its dials on the dark side in fixed order: an endpoint
        that refused a dial is demoted behind a backoff window and
        sorts LAST on subsequent sweeps."""
        from spark_rapids_tpu.server import SqlFrontDoor, WireClient
        from spark_rapids_tpu.server.protocol import ServerDraining
        door = SqlFrontDoor(session).start()
        try:
            dead_addr = ("127.0.0.1", _free_port())  # nobody listening
            live_addr = ("127.0.0.1", door.port)
            c = WireClient(*live_addr)
            try:
                # GOAWAY advertising the dark sibling FIRST: the sweep
                # dials it once, demotes it, then lands on the door
                c._failover(ServerDraining(
                    "drain", siblings=[dead_addr], retry_after_ms=1))
                assert c.goaways_survived == 1
                assert c._down[dead_addr][0] >= 1
                assert c.endpoints_demoted >= 1
                # while the demotion window holds, healthy endpoints
                # sort first and the dark one last
                c._down[dead_addr][1] = time.monotonic() + 30
                order = c._sweep_order([dead_addr, live_addr])
                assert order == [live_addr, dead_addr]
                # a second failover never re-dials the demoted side
                fails_before = c._down[dead_addr][0]
                c._failover(ServerDraining(
                    "again", siblings=[dead_addr], retry_after_ms=1))
                assert c.goaways_survived == 2
                assert c._down[dead_addr][0] == fails_before
                # ...and a successful dial restores full standing
                c._down[live_addr] = [3, time.monotonic() + 30]
                c._connect(live_addr)
                assert live_addr not in c._down
            finally:
                c.close()
        finally:
            door.close()


class TestResultStreamDeliveryCheck:
    def _run_stream(self, frames):
        """Feed a crafted frame sequence to WireClient._collect_result
        over a socketpair."""
        import socket as _socket

        from spark_rapids_tpu.server import WireClient
        from spark_rapids_tpu.server import protocol as P
        a, b = _socket.socketpair()
        try:
            def serve():
                for ftype, payload in frames:
                    P.send_frame(b, ftype, payload)

            t = threading.Thread(target=serve)
            t.start()
            c = object.__new__(WireClient)
            c._sock = a
            try:
                return c._collect_result()
            finally:
                t.join(timeout=10)
        finally:
            a.close()
            b.close()

    def _ipc(self):
        t = pa.table({"x": [1, 2, 3]})
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        return sink.getvalue().to_pybytes()

    def test_correct_count_passes(self):
        from spark_rapids_tpu.server import protocol as P
        meta = P.pack_json({"query_id": "q", "schema": []})
        rs = self._run_stream([
            (P.RSP_META, meta),
            (P.RSP_BATCH, self._ipc()),
            (P.RSP_END, P.pack_json({"batches": 1, "rows": 3})),
        ])
        assert rs.rows() == [(1,), (2,), (3,)]

    def test_duplicated_batch_frame_detected_typed(self):
        """A batch frame delivered twice (broken middlebox): the END
        count exposes it as a typed ProtocolError — rows are never
        silently double-counted."""
        from spark_rapids_tpu.server import protocol as P
        meta = P.pack_json({"query_id": "q", "schema": []})
        ipc = self._ipc()
        with pytest.raises(P.ProtocolError, match="duplicated or lost"):
            self._run_stream([
                (P.RSP_META, meta),
                (P.RSP_BATCH, ipc),
                (P.RSP_BATCH, ipc),  # the duplicate
                (P.RSP_END, P.pack_json({"batches": 1})),
            ])

    def test_lost_batch_frame_detected_typed(self):
        from spark_rapids_tpu.server import protocol as P
        meta = P.pack_json({"query_id": "q", "schema": []})
        with pytest.raises(P.ProtocolError, match="duplicated or lost"):
            self._run_stream([
                (P.RSP_META, meta),
                (P.RSP_END, P.pack_json({"batches": 2})),
            ])

    def test_reordered_end_before_batch_detected(self):
        """END arriving ahead of its batch (reordered delivery): the
        count mismatch surfaces typed at the decoder."""
        from spark_rapids_tpu.server import protocol as P
        meta = P.pack_json({"query_id": "q", "schema": []})
        with pytest.raises(P.ProtocolError, match="duplicated or lost"):
            self._run_stream([
                (P.RSP_META, meta),
                (P.RSP_END, P.pack_json({"batches": 1})),
            ])


# ---------------------------------------------------------------------------
# The @slow multi-process partition chaos differential.
# ---------------------------------------------------------------------------

def _write_shards(tmp, world, rows=600):
    import numpy as np
    import pyarrow.parquet as pq
    rng = np.random.default_rng(17)
    for r in range(world):
        n = rows
        t = pa.table({
            "k": rng.integers(0, 23, n),
            "s": rng.choice(["ab", "cd", "ef"], n),
            "v": rng.integers(0, 1000, n),
            "w": rng.random(n),
        })
        pq.write_table(t, os.path.join(tmp, f"part-{r}.parquet"))


def _run_world(tmp, out, world, port, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = []
    for r in range(world):
        cmd = [sys.executable, os.path.join(REPO, "tests",
                                            "dcn_worker.py"),
               "--rank", str(r), "--world", str(world),
               "--port", str(port), "--data", tmp, "--out", out,
               "--hb-interval", "0.2", "--hb-timeout", "1.0",
               "--wait-timeout", "60", "--quorum-window-ms", "4000",
               *extra]
        procs.append(subprocess.Popen(
            cmd, cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT))
    return procs


@pytest.mark.slow
class TestDupReorderMiniSuiteDifferential:
    def test_seeded_dup_reorder_rate_across_query_suite(self, tmp_path):
        """The distributed query mini-suite (grouped agg, top-k,
        shuffled join, broadcast join — every DCN collective and
        data-plane shape) under a seeded dup+reorder rate: results
        byte-identical to the clean distributed run, replays
        attributable (frames_deduped), zero leaked spill handles
        (asserted in-worker)."""
        import socket as _socket
        import numpy as np
        import pyarrow.parquet as pq
        data = str(tmp_path / "data")
        os.makedirs(data)
        _write_shards(data, 3)
        rng = np.random.default_rng(5)
        for r in range(3):
            pq.write_table(pa.table({
                "dk": np.arange(r * 8, r * 8 + 8),
                "dname": [f"d{r}-{i}" for i in range(8)],
            }), os.path.join(data, f"dim-{r}.parquet"))

        def free_port():
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        def norm(rows):
            return sorted((tuple(r) for r in rows),
                          key=lambda r: tuple(str(x) for x in r))

        for query in ("simple", "topk", "join", "bjoin"):
            outs = {}
            for tag, extra in (
                    ("clean", ()),
                    ("faulted", ("--net-dup-rate", "0.15",
                                 "--net-reorder-rate", "0.1",
                                 "--net-seed", "11"))):
                out = str(tmp_path / f"{query}-{tag}")
                procs = _run_world(data, out, 3, free_port(),
                                   extra=("--query", query, *extra))
                for p in procs:
                    log = p.communicate(timeout=300)[0].decode()
                    assert p.returncode == 0, \
                        f"{query}/{tag}:\n{log[-4000:]}"
                outs[tag] = [json.load(open(f"{out}.{r}"))
                             for r in range(3)]
                if tag == "faulted":
                    deduped = sum(
                        json.load(open(f"{out}.stats.{r}"))
                        ["frames_deduped"] for r in range(3))
                    assert deduped > 0, \
                        f"{query}: no dup/reorder ever replayed"
            for r in range(3):
                assert norm(outs["faulted"][r]) == norm(outs["clean"][r]), \
                    f"{query}: rank {r} diverged under dup/reorder"


@pytest.mark.slow
class TestPartitionChaosDifferentialMultiProcess:
    @pytest.mark.parametrize("world,cut,minority", [
        (3, "2|0+1", [2]),
        (5, "3+4|0+1+2", [3, 4]),
    ])
    def test_partition_mid_query_differential(self, tmp_path, world,
                                              cut, minority):
        import socket as _socket
        data = str(tmp_path / "data")
        os.makedirs(data)
        _write_shards(data, world)

        def free_port():
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        # fault-free oracle
        out0 = str(tmp_path / "clean")
        procs = _run_world(data, out0, world, free_port())
        for p in procs:
            log = p.communicate(timeout=300)[0].decode()
            assert p.returncode == 0, log[-4000:]
        clean = json.load(open(f"{out0}.0"))
        assert clean

        # partition the minority after 1 shuffle op on each rank, heal
        # at t+12s; majority must match the oracle byte-identically,
        # minority must park typed then rejoin after the heal
        out1 = str(tmp_path / "cut")
        procs = _run_world(
            data, out1, world, free_port(),
            extra=("--net-partition", cut, "--net-after", "1",
                   "--net-heal-s", "12", "--await-parked",
                   ",".join(str(r) for r in minority)))
        logs = []
        for p in procs:
            log = p.communicate(timeout=300)[0].decode()
            logs.append(log)
            assert p.returncode == 0, log[-4000:]
        def norm(rows):
            return sorted((tuple(r) for r in rows),
                          key=lambda r: tuple(str(x) for x in r))

        majority = [r for r in range(world) if r not in minority]
        for r in majority:
            # adoption appends the minority's partitions after a
            # survivor's own, so the row ORDER shifts — the values must
            # be identical, unrounded (same combine order per fragment)
            assert norm(json.load(open(f"{out1}.{r}"))) == norm(clean), \
                f"rank {r} diverged\n{logs[r]}"
        epochs = set()
        for r in majority:
            stats = json.load(open(f"{out1}.stats.{r}"))
            epochs.add(stats["final_epoch"])
        for r in minority:
            marker = json.load(open(f"{out1}.parked.{r}"))
            assert marker["parked"]
            assert marker["error"] in ("QuorumLostError", "QueryFaulted")
            assert marker["rejoined"], marker
        assert len(epochs) == 1  # survivors agree on the epoch

"""Bounded window frames: device sliding min/max + value-range frames.

Reference: GpuWindowExec.scala:1655 (running) / :2004 (double-pass) and
the bounded range-frame regime.  Device shapes here: sparse-table RMQ for
ROWS min/max, composite-searchsorted positions for bounded RANGE frames
(ops/window.py).  Brute-force python is the oracle.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.window import Window


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def _data(rng, n=400, nk=5):
    return pa.table({
        "k": pa.array(rng.integers(0, nk, n).astype(np.int64)),
        "t": pa.array(np.arange(n, dtype=np.int32)),
        "v": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
    })


def _oracle(table, frame, fn, lo, hi, range_frame=False):
    """Brute-force per-row window over (k partition, t order)."""
    ks = table.column("k").to_pylist()
    ts = table.column("t").to_pylist()
    vs = table.column("v").to_pylist()
    rows = sorted(range(len(ks)), key=lambda i: (ks[i], ts[i]))
    pos = {i: p for p, i in enumerate(rows)}
    out = {}
    for i in range(len(ks)):
        if range_frame:
            js = [j for j in range(len(ks))
                  if ks[j] == ks[i] and lo <= ts[j] - ts[i] <= hi]
        else:
            p = pos[i]
            js = [rows[q] for q in range(max(0, p + lo), p + hi + 1)
                  if q < len(rows) and ks[rows[q]] == ks[i]]
        vals = [vs[j] for j in js]
        out[(ks[i], ts[i])] = fn(vals) if vals else None
    return out


@pytest.mark.parametrize("agg,fn", [("min", min), ("max", max)])
def test_sliding_minmax_rows_on_device(sess, rng, agg, fn):
    t = _data(rng)
    w = Window.partition_by("k").order_by("t").rows_between(-3, 2)
    func = F.min(F.col("v")) if agg == "min" else F.max(F.col("v"))
    # assert the plan keeps the window on device
    sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
    try:
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"), func.over(w).alias("m"))
        rows = df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", False)
    want = _oracle(t, "rows", fn, -3, 2)
    for k, tt, m in rows:
        assert m == want[(k, tt)], (k, tt, m, want[(k, tt)])


def test_sliding_first_last_rows(sess, rng):
    t = _data(rng, n=200)
    w = Window.partition_by("k").order_by("t").rows_between(-2, 2)
    df = sess.create_dataframe(t).select(
        F.col("k"), F.col("t"),
        F.first(F.col("v")).over(w).alias("f"),
        F.last(F.col("v")).over(w).alias("l"))
    rows = df.collect()
    wf = _oracle(t, "rows", lambda vs: vs[0], -2, 2)
    wl = _oracle(t, "rows", lambda vs: vs[-1], -2, 2)
    for k, tt, f_, l_ in rows:
        assert f_ == wf[(k, tt)] and l_ == wl[(k, tt)]


def test_bounded_range_sum_avg_count_on_device(sess, rng):
    t = _data(rng, n=300)
    w = Window.partition_by("k").order_by("t").range_between(-5, 5)
    sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
    try:
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"),
            F.sum(F.col("v")).over(w).alias("s"),
            F.count(F.col("v")).over(w).alias("c"),
            F.avg(F.col("v")).over(w).alias("a"))
        rows = df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", False)
    ws = _oracle(t, "range", sum, -5, 5, range_frame=True)
    wc = _oracle(t, "range", len, -5, 5, range_frame=True)
    for k, tt, s_, c_, a_ in rows:
        assert s_ == ws[(k, tt)]
        assert c_ == wc[(k, tt)]
        assert abs(a_ - ws[(k, tt)] / wc[(k, tt)]) < 1e-9


def test_bounded_range_minmax_on_device(sess, rng):
    """min/max over a bounded range frame: capacity-wide sparse-table RMQ
    over composite-searchsorted positions (GpuWindowExec.scala:1655)."""
    t = _data(rng, n=150)
    w = Window.partition_by("k").order_by("t").range_between(-4, 4)
    sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
    try:
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"), F.min(F.col("v")).over(w).alias("m"),
            F.max(F.col("v")).over(w).alias("x"))
        rows = df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", False)
    wmin = _oracle(t, "range", min, -4, 4, range_frame=True)
    wmax = _oracle(t, "range", max, -4, 4, range_frame=True)
    for k, tt, m, x in rows:
        assert m == wmin[(k, tt)] and x == wmax[(k, tt)]


def test_half_unbounded_rows_minmax_on_device(sess, rng):
    t = _data(rng, n=150)
    for lo, hi in [(None, 2), (-3, None)]:
        spec = Window.partition_by("k").order_by("t")
        w = spec.rows_between(
            Window.unboundedPreceding if lo is None else lo,
            Window.unboundedFollowing if hi is None else hi)
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
        try:
            df = sess.create_dataframe(t).select(
                F.col("k"), F.col("t"),
                F.min(F.col("v")).over(w).alias("m"))
            rows = df.collect()
        finally:
            sess.conf.set(
                "spark.rapids.tpu.test.validateExecsOnTpu", False)
        want = _oracle(t, "rows", min, lo if lo is not None else -10**6,
                       hi if hi is not None else 10**6)
        for k, tt, m in rows:
            assert m == want[(k, tt)], (lo, hi, k, tt)


def test_descending_range_key_on_device(sess, rng):
    """RANGE frame over a DESCENDING key: preceding adds to the key
    (Spark desc-range semantics), mapped onto the ascending kernel by
    negation."""
    t = _data(rng, n=150)
    w = (Window.partition_by("k").order_by(F.col("t").desc())
         .range_between(-4, 2))
    sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
    try:
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"),
            F.sum(F.col("v")).over(w).alias("s"),
            F.max(F.col("v")).over(w).alias("x"))
        rows = df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", False)
    ks = t.column("k").to_pylist()
    ts = t.column("t").to_pylist()
    vs = t.column("v").to_pylist()
    for k, tt, s, x in rows:
        js = [j for j in range(len(ks))
              if ks[j] == k and -4 <= tt - ts[j] <= 2]
        vals = [vs[j] for j in js]
        assert s == sum(vals) and x == max(vals), (k, tt)


def test_int64_range_key_on_device(sess, rng):
    """64-bit range keys take the lexicographic-search path (no packed
    composite exists for bigint/timestamp)."""
    n = 150
    t = pa.table({
        "k": pa.array(rng.integers(0, 4, n).astype(np.int64)),
        "t": pa.array((np.arange(n) * (1 << 33)).astype(np.int64)),
        "v": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
    })
    lo, hi = -(3 << 33), (2 << 33)
    w = Window.partition_by("k").order_by("t").range_between(lo, hi)
    sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
    try:
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"),
            F.sum(F.col("v")).over(w).alias("s"),
            F.min(F.col("v")).over(w).alias("m"))
        rows = df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", False)
    ks = t.column("k").to_pylist()
    ts = t.column("t").to_pylist()
    vs = t.column("v").to_pylist()
    for k, tt, s, m in rows:
        vals = [vs[j] for j in range(n)
                if ks[j] == k and lo <= ts[j] - tt <= hi]
        assert s == sum(vals) and m == min(vals), (k, tt)


def test_ignore_nulls_bounded_first_last_on_device(sess, rng):
    n = 200
    vals = [None if i % 3 == 0 else int(v)
            for i, v in enumerate(rng.integers(-50, 50, n))]
    t = pa.table({
        "k": pa.array(rng.integers(0, 4, n).astype(np.int64)),
        "t": pa.array(np.arange(n, dtype=np.int32)),
        "v": pa.array(vals, type=pa.int64()),
    })
    w = Window.partition_by("k").order_by("t").rows_between(-3, 3)
    sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
    try:
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"),
            F.first(F.col("v"), ignore_nulls=True).over(w).alias("f"),
            F.last(F.col("v"), ignore_nulls=True).over(w).alias("l"))
        rows = df.collect()
    finally:
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", False)
    ks = t.column("k").to_pylist()
    ts = t.column("t").to_pylist()
    order = sorted(range(n), key=lambda i: (ks[i], ts[i]))
    pos = {i: p for p, i in enumerate(order)}
    for k, tt, f, l in rows:
        i = next(j for j in range(n) if ks[j] == k and ts[j] == tt)
        p = pos[i]
        js = [order[q] for q in range(max(0, p - 3), p + 4)
              if q < n and ks[order[q]] == k]
        vv = [vals[j] for j in js if vals[j] is not None]
        assert f == (vv[0] if vv else None), (k, tt)
        assert l == (vv[-1] if vv else None), (k, tt)


def test_asymmetric_rows_frames(sess, rng):
    t = _data(rng, n=150)
    for lo, hi in [(0, 3), (-4, 0), (-1, 1), (2, 5)]:
        w = Window.partition_by("k").order_by("t").rows_between(lo, hi)
        df = sess.create_dataframe(t).select(
            F.col("k"), F.col("t"), F.max(F.col("v")).over(w).alias("m"))
        rows = df.collect()
        want = _oracle(t, "rows", max, lo, hi)
        for k, tt, m in rows:
            assert m == want[(k, tt)], (lo, hi, k, tt)


def test_empty_frame_is_null(sess):
    """rows between 2 following and 3 following near the partition end."""
    t = pa.table({"k": pa.array([1, 1, 1], type=pa.int64()),
                  "t": pa.array([0, 1, 2], type=pa.int32()),
                  "v": pa.array([10, 20, 30], type=pa.int64())})
    w = Window.partition_by("k").order_by("t").rows_between(2, 3)
    df = sess.create_dataframe(t).select(
        F.col("t"), F.min(F.col("v")).over(w).alias("m"),
        F.sum(F.col("v")).over(w).alias("s"))
    rows = sorted(df.collect())
    assert rows[0][1] == 30 and rows[1][1] is None and rows[2][1] is None
    assert rows[0][2] == 30 and rows[1][2] is None and rows[2][2] is None

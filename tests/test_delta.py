"""Delta Lake: log replay, partition pruning, time travel, append/overwrite
commits (delta-lake module analog)."""

import json
import os

import pyarrow as pa
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_delta_write_read_roundtrip(session, tmp_path):
    t = pa.table({"k": pa.array([1, 2, 3], type=pa.int64()),
                  "v": pa.array([1.5, 2.5, 3.5]),
                  "s": pa.array(["a", "b", None])})
    path = str(tmp_path / "tbl")
    v = session.create_dataframe(t).write.delta(path)
    assert v == 0
    assert os.path.exists(os.path.join(
        path, "_delta_log", f"{0:020d}.json"))
    back = session.read_delta(path)
    assert sorted(back.collect(), key=str) == sorted(
        [(1, 1.5, "a"), (2, 2.5, "b"), (3, 3.5, None)], key=str)
    # schema came from the log's metaData, not file sniffing
    names = [f.name for f in back.schema]
    assert names == ["k", "v", "s"]


def test_delta_append_and_time_travel(session, tmp_path):
    path = str(tmp_path / "tbl")
    df1 = session.create_dataframe({"x": [1, 2]})
    df2 = session.create_dataframe({"x": [3]})
    assert df1.write.delta(path) == 0
    assert df2.write.mode("append").delta(path) == 1
    assert sorted(r[0] for r in session.read_delta(path).collect()) == \
        [1, 2, 3]
    assert sorted(r[0] for r in
                  session.read_delta(path, version=0).collect()) == [1, 2]


def test_delta_overwrite_removes_priors(session, tmp_path):
    path = str(tmp_path / "tbl")
    session.create_dataframe({"x": [1, 2]}).write.delta(path)
    session.create_dataframe({"x": [9]}).write.mode("overwrite").delta(path)
    assert [r[0] for r in session.read_delta(path).collect()] == [9]
    # time travel still sees the old data (files weren't deleted)
    assert sorted(r[0] for r in
                  session.read_delta(path, version=0).collect()) == [1, 2]


def test_delta_partitioned_with_pruning(session, tmp_path):
    f = F()
    path = str(tmp_path / "tbl")
    df = session.create_dataframe(
        {"p": pa.array([1, 1, 2, 2], type=pa.int64()),
         "v": pa.array([1.0, 2.0, 3.0, 4.0])})
    df.write.partitionBy("p").delta(path)
    # partitionValues recorded in the add actions
    with open(os.path.join(path, "_delta_log", f"{0:020d}.json")) as fh:
        adds = [json.loads(l)["add"] for l in fh if '"add"' in l]
    assert all(a["partitionValues"].get("p") in ("1", "2") for a in adds)
    back = session.read_delta(path)
    q = back.filter(f.col("p") == 2).select("v")
    assert sorted(r[0] for r in q.collect()) == [3.0, 4.0]
    # partition column typed from the log schema (int64), appended last
    sch = {fl.name: str(fl.dtype) for fl in back.schema}
    assert sch["p"] == "bigint"


def test_delta_mode_errors(session, tmp_path):
    path = str(tmp_path / "tbl")
    session.create_dataframe({"x": [1]}).write.delta(path)
    with pytest.raises(FileExistsError):
        session.create_dataframe({"x": [2]}).write.delta(path)
    # ignore returns current version without writing
    v = session.create_dataframe({"x": [2]}).write.mode("ignore").delta(path)
    assert v == 0
    assert [r[0] for r in session.read_delta(path).collect()] == [1]


def test_delta_delete(session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_delete
    from spark_rapids_tpu.sql import functions as f
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"k": [1, 2, 3, 4], "v": [10.0, 20.0, 30.0, 40.0]}).write.delta(path)
    v = delta_delete(session, path, f.col("k") >= 3)
    assert v == 1
    assert sorted(session.read_delta(path).collect()) == \
        [(1, 10.0), (2, 20.0)]
    # old version still fully readable
    assert len(session.read_delta(path, version=0).collect()) == 4


def test_delta_update(session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_update
    from spark_rapids_tpu.sql import functions as f
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"k": [1, 2, 3], "v": [10.0, 20.0, 30.0]}).write.delta(path)
    delta_update(session, path, {"v": f.col("v") * 100},
                 condition=f.col("k") == 2)
    assert sorted(session.read_delta(path).collect()) == \
        [(1, 10.0), (2, 2000.0), (3, 30.0)]


def test_delta_delete_partitioned_untouched_files(session, tmp_path):
    """Files in non-matching partitions are not rewritten."""
    import glob
    from spark_rapids_tpu.io.delta import delta_delete
    from spark_rapids_tpu.sql import functions as f
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"p": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]}) \
        .write.partitionBy("p").delta(path)
    files_before = set(glob.glob(os.path.join(path, "p=1", "*.parquet")))
    delta_delete(session, path, (f.col("p") == 2) & (f.col("v") > 3.0))
    files_after = set(glob.glob(os.path.join(path, "p=1", "*.parquet")))
    assert files_before == files_after  # p=1 untouched
    assert sorted(session.read_delta(path).collect(), key=str) == \
        sorted([(1.0, 1), (2.0, 1), (3.0, 2)], key=str)


def test_delta_merge_upsert(session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_merge
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"k": [1, 2, 3], "v": [10.0, 20.0, 30.0]}).write.delta(path)
    src = session.create_dataframe({"k": [2, 4], "v": [200.0, 400.0]})
    v = delta_merge(session, path, src, on=["k"])
    assert v == 1
    got = sorted(session.read_delta(path).collect())
    assert got == [(1, 10.0), (2, 200.0), (3, 30.0), (4, 400.0)]
    # time travel still shows the pre-merge state
    assert sorted(session.read_delta(path, version=0).collect()) == \
        [(1, 10.0), (2, 20.0), (3, 30.0)]


def test_delta_merge_delete_matched(session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_merge
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"k": [1, 2, 3], "v": [10.0, 20.0, 30.0]}).write.delta(path)
    src = session.create_dataframe({"k": [1, 3], "v": [0.0, 0.0]})
    delta_merge(session, path, src, on=["k"], matched="delete",
                insert_not_matched=False)
    assert session.read_delta(path).collect() == [(2, 20.0)]


def test_delta_merge_untouched_files_stay(session, tmp_path):
    import glob
    from spark_rapids_tpu.io.delta import delta_merge
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"p": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]}) \
        .write.partitionBy("p").delta(path)
    before = set(glob.glob(os.path.join(path, "p=1", "*.parquet")))
    src = session.create_dataframe({"p": [2], "v": [300.0]})
    delta_merge(session, path, src, on=["p"], insert_not_matched=False)
    after = set(glob.glob(os.path.join(path, "p=1", "*.parquet")))
    assert before == after  # p=1 files untouched
    got = sorted(session.read_delta(path).collect(), key=str)
    # both p=2 rows matched the single source row -> both updated
    assert got == sorted([(1.0, 1), (2.0, 1), (300.0, 2), (300.0, 2)],
                         key=str)


def test_delta_merge_partitioned_insert_lands_in_partition(session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_merge
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"p": [1, 2], "v": [10.0, 20.0]}).write.partitionBy("p").delta(path)
    src = session.create_dataframe({"p": [3], "v": [30.0]})
    delta_merge(session, path, src, on=["p"])
    assert os.path.isdir(os.path.join(path, "p=3"))
    got = sorted(session.read_delta(path).collect(), key=str)
    assert got == sorted([(10.0, 1), (20.0, 2), (30.0, 3)], key=str)


def test_delta_merge_multiple_matches_raises(session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_merge
    path = str(tmp_path / "tbl")
    session.create_dataframe({"k": [1], "v": [10.0]}).write.delta(path)
    src = session.create_dataframe({"k": [1, 1], "v": [1.0, 2.0]})
    with pytest.raises(RuntimeError, match="multiple source rows"):
        delta_merge(session, path, src, on=["k"], insert_not_matched=False)


def test_delta_merge_rejects_partition_update_and_missing_cols(
        session, tmp_path):
    from spark_rapids_tpu.io.delta import delta_merge
    path = str(tmp_path / "tbl")
    session.create_dataframe(
        {"p": [1], "k": [1], "v": [10.0]}).write.partitionBy("p").delta(path)
    src = session.create_dataframe({"k": [1], "p": [2], "v": [0.0]})
    with pytest.raises(ValueError, match="partition column"):
        delta_merge(session, path, src, on=["k"],
                    matched_set={"p": "p"}, insert_not_matched=False)
    narrow = session.create_dataframe({"k": [9], "v": [1.0]})
    with pytest.raises(ValueError, match="missing"):
        delta_merge(session, path, narrow, on=["k"])


class TestZOrder:
    """OPTIMIZE ZORDER BY (VERDICT r4 item 9): content-preserving
    rewrite clustered along the Morton curve of the z-columns
    (zorder/ZOrderRules.scala + GpuInterleaveBits analog)."""

    def test_zorder_preserves_content_and_clusters(self, session, tmp_path):
        import json
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq
        from spark_rapids_tpu.io.delta import delta_zorder, write_delta

        rng = np.random.default_rng(11)
        n = 8000
        t = pa.table({"x": rng.integers(0, 1000, n),
                      "y": rng.integers(0, 1000, n),
                      "v": rng.uniform(0, 1, n)})
        path = str(tmp_path / "zt")
        # two appends -> two scattered files
        write_delta(session.create_dataframe(t.slice(0, n // 2)), path)
        write_delta(session.create_dataframe(t.slice(n // 2)), path,
                    mode="append")
        before = sorted(session.read_delta(path).collect())
        v = delta_zorder(session, path, ["x", "y"],
                         target_file_rows=2000)
        after_df = session.read_delta(path)
        after = sorted(after_df.collect())
        assert after == before  # content identical
        # commitInfo records OPTIMIZE
        log = sorted((tmp_path / "zt" / "_delta_log").glob("*.json"))[-1]
        ops = [json.loads(l).get("commitInfo", {}).get("operation")
               for l in open(log)]
        assert "OPTIMIZE" in [o for o in ops if o]
        # clustering: each rewritten file's x-range is tighter than the
        # full span (scattered appends cover ~full range per file)
        from spark_rapids_tpu.io.delta import DeltaTable, _data_files
        tab = DeltaTable(path)
        spans = []
        for rel in tab.active:
            xs = pq.read_table(f"{path}/{rel}", columns=["x"])["x"]
            spans.append(int(pa.compute.max(xs).as_py())
                         - int(pa.compute.min(xs).as_py()))
        assert len(spans) >= 3
        assert min(spans) < 700, spans  # at least one tight file


class TestMergeCDF:
    """CDF-aware MERGE (delta-24x GpuMergeIntoCommand analog): update
    pre/post images, deletes, and inserts land in _change_data and read
    back via table_changes."""

    def _mk(self, session, tmp_path):
        import pyarrow as pa
        from spark_rapids_tpu.io.delta import write_delta
        path = str(tmp_path / "mc")
        t = pa.table({"k": [1, 2, 3, 4], "v": [10.0, 20.0, 30.0, 40.0]})
        write_delta(session.create_dataframe(t), path,
                    properties={"delta.enableChangeDataFeed": "true"})
        return path

    def test_merge_update_insert_cdf(self, session, tmp_path):
        import pyarrow as pa
        from spark_rapids_tpu.io.delta import delta_merge, table_changes
        path = self._mk(session, tmp_path)
        src = session.create_dataframe(
            pa.table({"k": [2, 3, 9], "v": [200.0, 300.0, 900.0]}))
        v = delta_merge(session, path, src, on=["k"])
        rows = table_changes(session, path, v, v).to_arrow().to_pylist()
        by_type = {}
        for r in rows:
            by_type.setdefault(r["_change_type"], []).append(
                (r["k"], r["v"]))
        assert sorted(by_type["update_preimage"]) == [(2, 20.0), (3, 30.0)]
        assert sorted(by_type["update_postimage"]) == [(2, 200.0),
                                                       (3, 300.0)]
        assert by_type["insert"] == [(9, 900.0)]
        got = sorted(session.read_delta(path).collect())
        assert got == [(1, 10.0), (2, 200.0), (3, 300.0), (4, 40.0),
                       (9, 900.0)]

    def test_merge_delete_cdf(self, session, tmp_path):
        import pyarrow as pa
        from spark_rapids_tpu.io.delta import delta_merge, table_changes
        path = self._mk(session, tmp_path)
        src = session.create_dataframe(
            pa.table({"k": [1, 4], "v": [0.0, 0.0]}))
        v = delta_merge(session, path, src, on=["k"], matched="delete",
                        insert_not_matched=False)
        rows = table_changes(session, path, v, v).to_arrow().to_pylist()
        dels = sorted((r["k"], r["v"]) for r in rows
                      if r["_change_type"] == "delete")
        assert dels == [(1, 10.0), (4, 40.0)]
        got = sorted(session.read_delta(path).collect())
        assert got == [(2, 20.0), (3, 30.0)]

"""collect_list/collect_set aggregates and explode/Generate
(GpuCollectList / GpuGenerateExec analogs; array columns ride as host
arrow list columns)."""

import pyarrow as pa
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_collect_list_grouped(session):
    f = F()
    df = session.create_dataframe(
        {"k": [1, 2, 1, 2, 1], "v": [10, 20, 30, 20, None]})
    got = dict(df.group_by("k").agg(
        f.collect_list(f.col("v")).alias("vs")).collect())
    assert got[1] == [10, 30]  # nulls skipped, order preserved
    assert got[2] == [20, 20]


def test_collect_set_dedups(session):
    f = F()
    df = session.create_dataframe({"k": [1, 1, 1], "s": ["a", "b", "a"]})
    got = df.group_by("k").agg(
        f.collect_set(f.col("s")).alias("ss")).collect()
    assert sorted(got[0][1]) == ["a", "b"]


def test_collect_list_ungrouped_and_roundtrip(session, tmp_path):
    f = F()
    df = session.create_dataframe({"v": [1.5, 2.5]})
    got = df.agg(f.collect_list(f.col("v")).alias("vs")).collect()
    assert got == [([1.5, 2.5],)]


def test_explode_roundtrip(session):
    f = F()
    df = session.create_dataframe({"k": [1, 2, 3], "v": [1, 2, 3]})
    lists = df.group_by("k").agg(f.collect_list(f.col("v")).alias("vs"))
    back = lists.explode("vs", out_name="v2")
    got = sorted(back.collect())
    assert got == [(1, 1), (2, 2), (3, 3)]


def test_explode_from_arrow_lists(session):
    t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                  "arr": pa.array([[10, 20], [], None],
                                  type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t)
    got = sorted(df.explode("arr", out_name="x").collect())
    assert got == [(1, 10), (1, 20)]  # empty + null arrays dropped
    outer = sorted(df.explode("arr", out_name="x", outer=True).collect(),
                   key=str)
    assert (2, None) in outer and (3, None) in outer and len(outer) == 4


def test_explode_placement(session):
    # numeric elements: device explode (offsets -> parent gather)
    t = pa.table({"arr": pa.array([[1]], type=pa.list_(pa.int64()))})
    plan = session.create_dataframe(t).explode("arr").explain_string()
    assert "! Generate" not in plan
    # string elements have no device representation -> CPU with a reason
    ts = pa.table({"arr": pa.array([["a"]], type=pa.list_(pa.string()))})
    plan_s = session.create_dataframe(ts).explode("arr").explain_string()
    assert "runs on CPU" in plan_s


# ---------------------------------------------------------------------------------
# Device GenerateExec (GpuGenerateExec analog): offsets -> parent gather.
# ---------------------------------------------------------------------------------

def test_device_explode_gathers_siblings(session):
    import numpy as np
    t = pa.table({
        "k": pa.array([10, 20, 30], pa.int64()),
        "s": pa.array(["a", "b", "c"]),
        "arr": pa.array([[1, 2], [], [3, 4, 5]], type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t).explode("arr", out_name="v")
    assert "! Generate" not in df.explain_string()
    rows = sorted(df.collect())
    assert rows == [(10, "a", 1), (10, "a", 2),
                    (30, "c", 3), (30, "c", 4), (30, "c", 5)]


def test_device_explode_outer_and_element_nulls(session):
    t = pa.table({
        "k": pa.array([1, 2, 3, 4], pa.int64()),
        "arr": pa.array([[7, None], None, [], [9]],
                        type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t).explode("arr", out_name="v",
                                             outer=True)
    key = lambda r: (r[0], r[1] is None, r[1] or 0)  # noqa: E731
    rows = sorted(df.collect(), key=key)
    assert rows == [(1, 7), (1, None), (2, None), (3, None), (4, 9)]
    # plain explode drops empty/null arrays but keeps null ELEMENTS
    inner = sorted(session.create_dataframe(t)
                   .explode("arr", out_name="v").collect(),
                   key=lambda r: (r[0], r[1] is None, r[1] or 0))
    assert inner == [(1, 7), (1, None), (4, 9)]


def test_device_explode_double_elements_then_agg(session):
    from spark_rapids_tpu.sql import functions as F
    t = pa.table({
        "k": pa.array([1, 1, 2], pa.int64()),
        "arr": pa.array([[1.5, 2.5], [3.0], [10.0, 20.0]],
                        type=pa.list_(pa.float64()))})
    df = session.create_dataframe(t).explode("arr", out_name="v")
    got = sorted(df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
                 .collect())
    assert got == [(1, 7.0), (2, 30.0)]


def test_device_explode_splits_large_output(session):
    """Output rows (sum of list lengths) split to batchSizeRows-sized
    device batches instead of one giant allocation."""
    import spark_rapids_tpu as srt
    import numpy as np
    srt.Session.reset()
    s = srt.Session.get_or_create(settings={
        "spark.rapids.tpu.sql.batchSizeRows": 64})
    try:
        lists = [list(range(i * 10, i * 10 + 10)) for i in range(30)]
        t = pa.table({"k": pa.array(range(30), pa.int64()),
                      "arr": pa.array(lists, type=pa.list_(pa.int64()))})
        df = s.create_dataframe(t).explode("arr", out_name="v")
        rows = df.collect()
        assert len(rows) == 300
        got = sorted(v for _, v in rows)
        assert got == list(range(300))
        ks = sorted(k for k, _ in rows)
        assert ks == sorted(np.repeat(np.arange(30), 10).tolist())
    finally:
        srt.Session.reset()


def test_cpu_explode_keeps_null_string_elements(session):
    """String-element explode runs on the CPU path; null ELEMENTS must
    survive (only empty/null ARRAYS drop) — matching Spark and the device
    path's semantics for numeric elements."""
    t = pa.table({
        "k": pa.array([1, 2, 3], pa.int64()),
        "arr": pa.array([["a", None, "b"], [], None],
                        type=pa.list_(pa.string()))})
    df = session.create_dataframe(t)
    key = lambda r: (r[0], r[1] is None, r[1] or "")  # noqa: E731
    got = sorted(df.explode("arr", out_name="s").collect(), key=key)
    assert got == [(1, "a"), (1, "b"), (1, None)]
    outer = sorted(df.explode("arr", out_name="s", outer=True).collect(),
                   key=key)
    assert outer == [(1, "a"), (1, "b"), (1, None), (2, None), (3, None)]

"""collect_list/collect_set aggregates and explode/Generate
(GpuCollectList / GpuGenerateExec analogs; array columns ride as host
arrow list columns)."""

import pyarrow as pa
import pytest


def F():
    from spark_rapids_tpu.sql import functions
    return functions


def test_collect_list_grouped(session):
    f = F()
    df = session.create_dataframe(
        {"k": [1, 2, 1, 2, 1], "v": [10, 20, 30, 20, None]})
    got = dict(df.group_by("k").agg(
        f.collect_list(f.col("v")).alias("vs")).collect())
    assert got[1] == [10, 30]  # nulls skipped, order preserved
    assert got[2] == [20, 20]


def test_collect_set_dedups(session):
    f = F()
    df = session.create_dataframe({"k": [1, 1, 1], "s": ["a", "b", "a"]})
    got = df.group_by("k").agg(
        f.collect_set(f.col("s")).alias("ss")).collect()
    assert sorted(got[0][1]) == ["a", "b"]


def test_collect_list_ungrouped_and_roundtrip(session, tmp_path):
    f = F()
    df = session.create_dataframe({"v": [1.5, 2.5]})
    got = df.agg(f.collect_list(f.col("v")).alias("vs")).collect()
    assert got == [([1.5, 2.5],)]


def test_explode_roundtrip(session):
    f = F()
    df = session.create_dataframe({"k": [1, 2, 3], "v": [1, 2, 3]})
    lists = df.group_by("k").agg(f.collect_list(f.col("v")).alias("vs"))
    back = lists.explode("vs", out_name="v2")
    got = sorted(back.collect())
    assert got == [(1, 1), (2, 2), (3, 3)]


def test_explode_from_arrow_lists(session):
    t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                  "arr": pa.array([[10, 20], [], None],
                                  type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t)
    got = sorted(df.explode("arr", out_name="x").collect())
    assert got == [(1, 10), (1, 20)]  # empty + null arrays dropped
    outer = sorted(df.explode("arr", out_name="x", outer=True).collect(),
                   key=str)
    assert (2, None) in outer and (3, None) in outer and len(outer) == 4


def test_explode_plan_reason(session):
    t = pa.table({"arr": pa.array([[1]], type=pa.list_(pa.int64()))})
    plan = session.create_dataframe(t).explode("arr").explain_string()
    assert "CPU" in plan and "array" in plan

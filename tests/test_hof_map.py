"""Higher-order array functions (higherOrderFunctions.scala:291) and MAP
type operations (complexTypeCreator.scala:84 GpuCreateMap,
complexTypeExtractors.scala, collectionOperations.scala), differential
against python oracles."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def _arr_df(sess):
    t = pa.table({
        "a": pa.array([[1, 2, 3], [], None, [4, None, 6], [7]],
                      type=pa.list_(pa.int64())),
        "b": pa.array([[10, 20], [30], [40], None, [50, 60, 70]],
                      type=pa.list_(pa.int64())),
        "base": pa.array([100, 200, 300, 400, 500], type=pa.int64()),
    })
    return sess.create_dataframe(t)


class TestHigherOrder:
    def test_transform(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.transform(F.col("a"), lambda x: x * 2)
                         .alias("o")).collect()]
        assert got == [[2, 4, 6], [], None, [8, None, 12], [14]]

    def test_transform_with_index(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.transform(F.col("a"), lambda x, i: x + i)
                         .alias("o")).collect()]
        assert got == [[1, 3, 5], [], None, [4, None, 8], [7]]

    def test_transform_captures_outer_column(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.transform(F.col("a"),
                                     lambda x: x + F.col("base"))
                         .alias("o")).collect()]
        assert got == [[101, 102, 103], [], None, [404, None, 406], [507]]

    def test_filter(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.filter(F.col("a"), lambda x: x > 2)
                         .alias("o")).collect()]
        assert got == [[3], [], None, [4, 6], [7]]

    def test_exists_three_valued(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.exists(F.col("a"), lambda x: x > 5)
                         .alias("o")).collect()]
        # row 3: [4, None, 6] -> True (6>5); row 0: all false -> False
        assert got == [False, False, None, True, True]
        got2 = [r[0] for r in
                df.select(F.exists(F.col("a"), lambda x: x > 4)
                          .alias("o")).collect()]
        # [4, None, 6]: 6>4 True
        assert got2[3] is True

    def test_exists_null_makes_unknown(self, sess):
        t = pa.table({"a": pa.array([[1, None, 2]],
                                    type=pa.list_(pa.int64()))})
        df = sess.create_dataframe(t)
        got = df.select(F.exists(F.col("a"), lambda x: x > 5)
                        .alias("o")).collect()
        assert got[0][0] is None  # no TRUE, one NULL -> NULL

    def test_forall(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.forall(F.col("a"), lambda x: x > 0)
                         .alias("o")).collect()]
        # [] -> True (vacuous); [4,None,6] -> NULL (no false, one null)
        assert got == [True, True, None, None, True]

    def test_aggregate_fold(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.aggregate(F.col("a"), F.lit(0),
                                     lambda acc, x: acc + x)
                         .alias("o")).collect()]
        assert got[0] == 6 and got[1] == 0 and got[2] is None
        assert got[4] == 7

    def test_aggregate_with_finish(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.aggregate(F.col("b"), F.lit(0),
                                     lambda acc, x: acc + x,
                                     lambda acc: acc * 10)
                         .alias("o")).collect()]
        assert got == [300, 300, 400, None, 1800]

    def test_zip_with(self, sess):
        df = _arr_df(sess)
        got = [r[0] for r in
               df.select(F.zip_with(F.col("a"), F.col("b"),
                                    lambda x, y: x + y)
                         .alias("o")).collect()]
        assert got[0] == [11, 22, None]  # b shorter: null-padded
        assert got[1] == [None]
        assert got[2] is None and got[3] is None
        assert got[4] == [57, None, None]

    def test_transform_strings(self, sess):
        t = pa.table({"s": pa.array([["ab", "c"], ["de"]],
                                    type=pa.list_(pa.string()))})
        df = sess.create_dataframe(t)
        got = [r[0] for r in
               df.select(F.transform(
                   F.col("s"), lambda x: F.upper(x)).alias("o"))
               .collect()]
        assert got == [["AB", "C"], ["DE"]]

    def test_hof_in_filter_predicate(self, sess):
        df = _arr_df(sess)
        got = df.filter(F.exists(F.col("a"), lambda x: x == 7)).collect()
        assert len(got) == 1 and got[0][2] == 500


class TestMap:
    def _map_df(self, sess):
        t = pa.table({
            "m": pa.array([[("a", 1), ("b", 2)], [], None,
                           [("c", 3), ("d", None)]],
                          type=pa.map_(pa.string(), pa.int64())),
            "k": pa.array(["a", "x", "a", "d"]),
        })
        return sess.create_dataframe(t)

    def test_map_roundtrip_and_keys_values(self, sess):
        df = self._map_df(sess)
        rows = df.select(F.map_keys(F.col("m")).alias("ks"),
                         F.map_values(F.col("m")).alias("vs")).collect()
        assert rows[0] == (["a", "b"], [1, 2])
        assert rows[1] == ([], [])
        assert rows[2] == (None, None)
        assert rows[3][0] == ["c", "d"]

    def test_element_at_map(self, sess):
        df = self._map_df(sess)
        got = [r[0] for r in
               df.select(F.element_at(F.col("m"), F.col("k"))
                         .alias("o")).collect()]
        assert got == [1, None, None, None]

    def test_create_map_and_concat(self, sess):
        t = pa.table({"x": pa.array([1, 2], type=pa.int64()),
                      "y": pa.array([10.0, 20.0])})
        df = sess.create_dataframe(t)
        rows = df.select(
            F.map_concat(F.create_map(F.lit("x"), F.col("x")),
                         F.create_map(F.lit("x"), F.col("x") + 100,
                                      F.lit("z"), F.lit(9)))
            .alias("m")).collect()
        # duplicate key: last wins
        assert dict(rows[0][0]) == {"x": 101, "z": 9}
        assert dict(rows[1][0]) == {"x": 102, "z": 9}

    def test_map_from_arrays_entries_roundtrip(self, sess):
        t = pa.table({
            "ks": pa.array([["p", "q"], ["r"]],
                           type=pa.list_(pa.string())),
            "vs": pa.array([[1, 2], [3]], type=pa.list_(pa.int64())),
        })
        df = sess.create_dataframe(t)
        rows = df.select(
            F.map_entries(F.map_from_arrays(F.col("ks"), F.col("vs")))
            .alias("e")).collect()
        assert rows[0][0] == [{"key": "p", "value": 1},
                              {"key": "q", "value": 2}]
        assert rows[1][0] == [{"key": "r", "value": 3}]

    def test_map_filter_transform(self, sess):
        df = self._map_df(sess)
        rows = df.select(
            F.map_filter(F.col("m"), lambda k, v: v > 1).alias("f"),
            F.transform_values(F.col("m"),
                               lambda k, v: v * 10).alias("tv")).collect()
        assert dict(rows[0][0]) == {"b": 2}
        assert dict(rows[0][1]) == {"a": 10, "b": 20}
        assert rows[2][0] is None
        assert dict(rows[3][1]) == {"c": 30, "d": None}

    def test_transform_keys(self, sess):
        df = self._map_df(sess)
        rows = df.select(
            F.transform_keys(F.col("m"),
                             lambda k, v: F.concat(k, F.lit("!")))
            .alias("tk")).collect()
        assert dict(rows[0][0]) == {"a!": 1, "b!": 2}

    def test_group_by_map_values_pipeline(self, sess):
        """MAP columns survive project/filter pipelines."""
        df = self._map_df(sess)
        got = (df.filter(F.col("m").is_not_null())
               .select(F.size(F.col("m")).alias("n")).collect())
        assert [r[0] for r in got] == [2, 0, 2]

"""Differential acceptance for the TPC-DS starter queries
(models/tpcds.py): engine vs pandas oracle through the parquet scan path
at a tiny scale factor — same registry bench.py times at SF1."""

import pytest

from spark_rapids_tpu.models import tpcds
from spark_rapids_tpu.models.tpch_suite import rows_rel_err


@pytest.fixture(scope="module")
def db(session, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("tpcds_tiny"))
    dfs = tpcds.load_db(session, 0.01, out)
    pds = tpcds.load_pdb(0.01, out)
    return dfs, pds


@pytest.mark.parametrize("name", sorted(tpcds.QUERIES))
def test_tpcds_query_differential(db, name):
    dfs, pds = db
    runner, oracle = tpcds.QUERIES[name]
    got = runner(dfs)
    want = oracle(pds)
    err = rows_rel_err(got, want)
    assert err < 1e-6, f"{name}: rel_err={err} ({len(got)} rows)"

"""Differential test oracle + seeded data generators.

Port of the reference's integration-test core (SURVEY.md §4.3):
``assert_gpu_and_cpu_are_equal_collect`` (asserts.py:560) becomes
``assert_tpu_and_oracle_equal`` — run the query through the engine and
compare against a pandas/pyarrow oracle; the seeded generator family
mirrors data_gen.py:38-735.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np
import pandas as pd


# ---------------------------------------------------------------------------------
# Oracle comparison
# ---------------------------------------------------------------------------------

def normalize_pdf(pdf: pd.DataFrame) -> pd.DataFrame:
    out = pdf.copy()
    for c in out.columns:
        if str(out[c].dtype).startswith(("Int", "UInt", "Float")):
            out[c] = out[c].astype(object).where(out[c].notna(), None)
    return out.reset_index(drop=True)


def assert_rows_equal(actual_rows, expected_rows, approx_float=False,
                      ignore_order=True):
    def key(r):
        return tuple((x is None, _orderable(x)) for x in r)
    if ignore_order:
        actual_rows = sorted(actual_rows, key=key)
        expected_rows = sorted(expected_rows, key=key)
    assert len(actual_rows) == len(expected_rows), (
        f"row count {len(actual_rows)} != {len(expected_rows)}\n"
        f"actual={actual_rows[:10]}\nexpected={expected_rows[:10]}")
    for i, (a, e) in enumerate(zip(actual_rows, expected_rows)):
        assert len(a) == len(e), f"row {i}: arity {len(a)} vs {len(e)}"
        for j, (av, ev) in enumerate(zip(a, e)):
            assert _val_eq(av, ev, approx_float), (
                f"row {i} col {j}: {av!r} != {ev!r}\n"
                f"actual row={a}\nexpected row={e}")


def _orderable(x):
    if x is None:
        return ""
    if isinstance(x, float) and math.isnan(x):
        return "nan"
    return str(x)


def _val_eq(a, b, approx_float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if approx_float:
            return math.isclose(fa, fb, rel_tol=1e-6, abs_tol=1e-9)
        return fa == fb or math.isclose(fa, fb, rel_tol=1e-12, abs_tol=1e-12)
    return a == b


def pdf_rows(pdf: pd.DataFrame):
    rows = []
    for t in pdf.itertuples(index=False):
        row = []
        for x in t:
            # pd.NA / None / NaT are SQL nulls; float NaN is a real value
            if x is None or x is pd.NA or x is pd.NaT:
                row.append(None)
            elif not isinstance(x, (float, np.floating)) and pd.isna(x):
                row.append(None)
            else:
                row.append(x.item() if hasattr(x, "item") else x)
        rows.append(tuple(row))
    return rows


def assert_df_matches_pandas(df, expected: pd.DataFrame, approx_float=False,
                             ignore_order=True):
    """df: engine DataFrame; expected: pandas oracle result."""
    actual = df.collect()
    expected_rows = pdf_rows(expected)
    assert_rows_equal(actual, expected_rows, approx_float, ignore_order)


# ---------------------------------------------------------------------------------
# Seeded generators (data_gen.py analog)
# ---------------------------------------------------------------------------------

class Gen:
    def __init__(self, nullable=True, null_prob=0.1):
        self.nullable = nullable
        self.null_prob = null_prob

    def generate(self, rng: np.random.Generator, n: int):
        vals = self._gen(rng, n)
        if self.nullable:
            mask = rng.random(n) < self.null_prob
            vals = [None if m else v for v, m in zip(vals, mask)]
        return vals

    def _gen(self, rng, n):
        raise NotImplementedError


class IntGen(Gen):
    def __init__(self, lo=-(2 ** 31), hi=2 ** 31 - 1, dtype="int32", **kw):
        super().__init__(**kw)
        self.lo, self.hi, self.dtype = lo, hi, dtype

    def _gen(self, rng, n):
        return [int(x) for x in rng.integers(self.lo, self.hi, n)]


class LongGen(IntGen):
    def __init__(self, lo=-(2 ** 63), hi=2 ** 63 - 1, **kw):
        super().__init__(lo, hi, "int64", **kw)


class DoubleGen(Gen):
    def __init__(self, special=True, **kw):
        super().__init__(**kw)
        self.special = special

    def _gen(self, rng, n):
        vals = list((rng.random(n) - 0.5) * 2e6)
        if self.special and n >= 8:
            for i, sp in enumerate([0.0, -0.0, float("nan"), float("inf"),
                                    float("-inf"), 1e-300, -1e300, 1.5]):
                vals[int(rng.integers(0, n))] = sp
        return [float(v) for v in vals]


class FloatGen(DoubleGen):
    def _gen(self, rng, n):
        return [float(np.float32(v)) for v in super()._gen(rng, n)]


class BoolGen(Gen):
    def _gen(self, rng, n):
        return [bool(b) for b in rng.integers(0, 2, n)]


class StringGen(Gen):
    def __init__(self, alphabet="abcdefgXYZ 0123456789", max_len=12, **kw):
        super().__init__(**kw)
        self.alphabet = alphabet
        self.max_len = max_len

    def _gen(self, rng, n):
        out = []
        for _ in range(n):
            ln = int(rng.integers(0, self.max_len))
            out.append("".join(rng.choice(list(self.alphabet), ln)))
        return out


class DateGen(Gen):
    def _gen(self, rng, n):
        import datetime
        base = datetime.date(1970, 1, 1)
        return [base + datetime.timedelta(days=int(d))
                for d in rng.integers(-20000, 20000, n)]


class TimestampGen(Gen):
    def _gen(self, rng, n):
        import datetime
        base = datetime.datetime(2000, 1, 1)
        return [base + datetime.timedelta(microseconds=int(us))
                for us in rng.integers(-10 ** 15, 10 ** 15, n)]


def gen_table(rng, gens: dict, n: int):
    """dict name->Gen → (pyarrow.Table, pandas oracle with nullable dtypes
    so SQL null stays distinct from float NaN)."""
    import pyarrow as pa
    cols = {name: g.generate(rng, n) for name, g in gens.items()}
    table = pa.table({k: pa.array(v) for k, v in cols.items()})
    # Nullable dtypes for ints/bools/strings keep SQL null distinct from NaN.
    # Floats stay plain float64: pandas' masked Float64 folds genuine NaN into
    # NA, which breaks the oracle — so float columns in generated tables
    # should be non-nullable (dedicated null tests build literal frames).
    mapper = {pa.int8(): pd.Int8Dtype(), pa.int16(): pd.Int16Dtype(),
              pa.int32(): pd.Int32Dtype(), pa.int64(): pd.Int64Dtype(),
              pa.bool_(): pd.BooleanDtype(), pa.string(): pd.StringDtype()}
    return table, table.to_pandas(types_mapper=mapper.get)

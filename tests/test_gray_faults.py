"""Gray-failure survival (ISSUE 7): end-to-end data integrity
(checksums on every durable byte path), the per-query hang watchdog,
and straggler hedging for DCN fragment fetches.

The mixed chaos differential at the bottom is the acceptance gate:
seeded GRAY faults (corruption) combined with a FAIL-STOP peer kill on
a thread-rank world must still produce results identical to the
fault-free run, with recovery attributable and zero leaked handles.
"""

import errno
import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import ALL_ENTRIES, TpuConf
from spark_rapids_tpu.faults import (INJECTOR, IntegrityFault,
                                     PermanentFault, QueryFaulted,
                                     check_disk_full)
from spark_rapids_tpu.faults import integrity
from spark_rapids_tpu.memory.spill import get_catalog
from spark_rapids_tpu.parallel.host_shuffle import (HostShuffle,
                                                    gc_orphan_frames,
                                                    iter_frames,
                                                    verify_stream)
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.utils.metrics import QueryStats

FAST = {
    "spark.rapids.tpu.faults.backoff.baseMs": 1.0,
    "spark.rapids.tpu.faults.backoff.maxMs": 10.0,
}


@pytest.fixture()
def gray_session(session):
    keys = [k for k in ALL_ENTRIES
            if k.startswith(("spark.rapids.tpu.faults.",
                             "spark.rapids.tpu.sql.trace.",
                             "spark.rapids.tpu.shuffle.",
                             "spark.rapids.tpu.sql.cache."))]
    for k, v in FAST.items():
        session.conf.set(k, v)
    yield session
    for k in keys:
        session.conf.unset(k)
    INJECTOR.arm()
    from spark_rapids_tpu.cache import clear_query_cache
    clear_query_cache()


@pytest.fixture()
def fast_backoff():
    for k, v in FAST.items():
        TpuConf.set_session(k, v)
    yield
    for k in FAST:
        TpuConf.unset_session(k)
    INJECTOR.arm()


def _frame(n=2000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "a": np.arange(n, dtype=np.int64),
        "b": rng.random(n),
        "k": rng.integers(0, 9, n).astype(np.int64),
    })


def _write_pq(tmp_path, name, pdf):
    path = str(tmp_path / name)
    pq.write_table(pa.Table.from_pandas(pdf, preserve_index=False), path)
    return path


def _agg_rows(sess, path):
    df = sess.read_parquet(path)
    return sorted(df.filter(F.col("b") < 0.7).group_by("k").agg(
        F.sum(F.col("a")).alias("s")).collect())


# ---------------------------------------------------------------------------
# Integrity primitives.
# ---------------------------------------------------------------------------

class TestIntegrityUnit:
    def test_checksum_stable_and_sensitive(self):
        data = b"the quick brown fox" * 100
        c = integrity.checksum(data)
        assert c == integrity.checksum(data)
        assert c != integrity.checksum(integrity.flip(data))

    def test_verify_mismatch_typed_and_counted(self):
        data = b"payload bytes"
        crc = integrity.checksum(data)
        integrity.verify(data, crc, what="unit")  # clean passes
        s0 = QueryStats.get().snapshot()
        with pytest.raises(IntegrityFault) as ei:
            integrity.verify(integrity.flip(data), crc, what="unit",
                             point="shuffle.fragment")
        assert ei.value.point == "shuffle.fragment"
        assert QueryStats.delta_since(s0)["integrity_failures"] == 1

    def test_verify_disabled_passes_through(self):
        conf = TpuConf({
            "spark.rapids.tpu.faults.integrity.enabled": False})
        integrity.verify(b"anything", 12345, what="unit", conf=conf)

    def test_integrity_fault_is_transient(self):
        from spark_rapids_tpu.faults import TransientFault
        assert issubclass(IntegrityFault, TransientFault)

    def test_file_sidecar_roundtrip(self, tmp_path):
        p = str(tmp_path / "data.bin")
        with open(p, "wb") as f:
            f.write(b"x" * 4096)
        integrity.write_sidecar(p)
        side = integrity.sidecar_path(p)
        assert os.path.basename(side).startswith(".")
        integrity.verify_file(p)  # clean
        with open(p, "r+b") as f:
            f.seek(100)
            f.write(b"Y")
        with pytest.raises(IntegrityFault):
            integrity.verify_file(p)
        integrity.remove_sidecar(p)
        integrity.verify_file(p)  # no sidecar: nothing stamped


# ---------------------------------------------------------------------------
# Shuffle frame integrity: file AND wire format.
# ---------------------------------------------------------------------------

class TestFrameIntegrity:
    def test_corrupt_frame_detected_and_healed(self, tmp_path):
        from spark_rapids_tpu.faults import transient_retry
        conf = TpuConf(FAST)
        sh = HostShuffle(1, str(tmp_path), num_threads=1)
        try:
            sh.write_partition(0, pa.table({"x": list(range(50))}))
            sh.finish_writes()
            clean = [t.to_pydict() for t in sh.read_partition(0)]
            INJECTOR.arm(schedule="shuffle.corrupt:1")
            s0 = QueryStats.get().snapshot()
            tables = transient_retry(
                conf, "shuffle.fragment",
                lambda: list(sh.read_partition(0)),
                recover_counter="fragments_recomputed")
            d = QueryStats.delta_since(s0)
            assert [t.to_pydict() for t in tables] == clean
            assert d["integrity_failures"] >= 1
            assert d["fragments_recomputed"] == 1
        finally:
            INJECTOR.arm()
            sh.close()

    def test_stream_verify_catches_wire_corruption(self, tmp_path):
        sh = HostShuffle(1, str(tmp_path), num_threads=1)
        try:
            sh.write_partition(0, pa.table({"x": [1, 2, 3]}))
            sh.finish_writes()
            with open(sh._paths[0], "rb") as f:
                raw = f.read()
            verify_stream(raw)  # the file bytes ARE the wire payload
            assert sum(t.num_rows for t in iter_frames(raw)) == 3
            bad = bytearray(raw)
            bad[len(bad) // 2] ^= 0x01
            with pytest.raises(IntegrityFault):
                verify_stream(bytes(bad))
        finally:
            sh.close()


# ---------------------------------------------------------------------------
# Written-file integrity: sidecars stamped at the atomic commit point,
# verified at scan.
# ---------------------------------------------------------------------------

class TestWriterIntegrity:
    def test_sidecar_stamped_and_hidden(self, gray_session, tmp_path):
        s = gray_session
        src = _write_pq(tmp_path, "src.parquet", _frame(n=400))
        out = str(tmp_path / "out")
        s.read_parquet(src).write.mode("overwrite").parquet(out)
        files = os.listdir(out)
        sidecars = [f for f in files if f.endswith(".crc")]
        assert sidecars and all(f.startswith(".") for f in sidecars)
        # listings skip dot-files: read-back sees only the data
        back = s.read_parquet(out).collect()
        assert len(back) == 400

    def test_corrupt_published_file_fails_typed(self, gray_session,
                                                tmp_path):
        s = gray_session
        src = _write_pq(tmp_path, "src.parquet", _frame(n=400, seed=5))
        out = str(tmp_path / "out2")
        s.read_parquet(src).write.mode("overwrite").parquet(out)
        data_file = [f for f in os.listdir(out)
                     if f.endswith(".parquet")][0]
        p = os.path.join(out, data_file)
        with open(p, "r+b") as f:
            f.seek(128)
            b = f.read(1)
            f.seek(128)
            f.write(bytes([b[0] ^ 1]))
        s.conf.set("spark.rapids.tpu.faults.recovery.enabled", False)
        with pytest.raises(QueryFaulted) as ei:
            s.read_parquet(out).collect()
        assert ei.value.point == "io.read"
        s.conf.unset("spark.rapids.tpu.faults.recovery.enabled")
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# ENOSPC: disk-full is permanent at this placement, not a retry loop.
# ---------------------------------------------------------------------------

class TestDiskFull:
    def test_check_disk_full_types_enospc(self):
        with pytest.raises(PermanentFault, match="disk full"):
            check_disk_full(OSError(errno.ENOSPC, "No space left"),
                            "io.write")
        # other OSErrors pass through untouched
        check_disk_full(OSError(errno.EIO, "io error"), "io.write")

    def test_writer_enospc_fast_fails_resubmittable(self, gray_session,
                                                    tmp_path,
                                                    monkeypatch):
        from spark_rapids_tpu.io.writers import _RollingFileWriter
        s = gray_session
        src = _write_pq(tmp_path, "src.parquet", _frame(n=300, seed=7))
        out = str(tmp_path / "full")

        def _no_space(self, chunk):
            raise OSError(errno.ENOSPC, "No space left on device")

        monkeypatch.setattr(_RollingFileWriter, "_write_chunk", _no_space)
        t0 = time.monotonic()
        with pytest.raises(PermanentFault, match="disk full"):
            s.read_parquet(src).write.mode("overwrite").parquet(out)
        # fast-fail: no backoff curve was ridden against a full disk
        assert time.monotonic() - t0 < 2.0
        # atomicity held: nothing was published
        leftovers = os.listdir(out) if os.path.exists(out) else []
        assert not [f for f in leftovers if f.endswith(".parquet")]
        get_catalog().assert_no_leaks()

    def test_spill_enospc_types_permanent(self, tmp_path, monkeypatch):
        import builtins

        import jax.numpy as jnp

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.batch import (ColumnBatch, DeviceColumn,
                                            Field, Schema)
        from spark_rapids_tpu.memory.spill import SpillCatalog
        cat = SpillCatalog(1 << 30, 1 << 30,
                           spill_dir=str(tmp_path / "spill"))
        h = cat.register(ColumnBatch(
            Schema([Field("x", T.INT64, False)]),
            [DeviceColumn(T.INT64, jnp.arange(4))], 4))
        h.spill_to_host()
        real_open = builtins.open

        def failing_open(path, mode="r", *a, **kw):
            if "wb" in mode and "srt-spill" in str(path):
                raise OSError(errno.ENOSPC, "No space left on device")
            return real_open(path, mode, *a, **kw)

        monkeypatch.setattr(builtins, "open", failing_open)
        with pytest.raises(PermanentFault, match="disk full"):
            h.spill_to_disk()
        monkeypatch.undo()
        # the handle survives (still HOST) and closes clean
        assert h.state == h.HOST
        h.close()
        cat.assert_no_leaks()


# ---------------------------------------------------------------------------
# Watchdog: stalls detected within the window, no false positives on
# slow-but-alive queries.
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_hung_query_reclaimed_within_bound(self, gray_session,
                                               tmp_path):
        s = gray_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=2500, seed=9))
        clean = _agg_rows(s, path)  # warm: compiles out of the window
        stall_ms = 300.0
        s.conf.set("spark.rapids.tpu.sql.trace.enabled", True)
        s.conf.set("spark.rapids.tpu.faults.watchdog.stallMs", stall_ms)
        s.conf.set("spark.rapids.tpu.faults.resubmit.max", 0)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "device.hang:1")
        t0 = time.monotonic()
        h = s.submit(lambda: _agg_rows(s, path), label="wd-hang")
        with pytest.raises(QueryFaulted) as ei:
            h.result(timeout=60)
        elapsed = time.monotonic() - t0
        assert ei.value.resubmittable
        # reclaimed within stallMs + one poll + one batch, not minutes
        # (generous 10x bound to keep CI timing-safe)
        assert elapsed < (stall_ms / 1000.0) * 10
        assert h.status == "faulted"
        assert s.scheduler().running() == 0
        tr = h.trace()
        assert tr is not None and tr.status == "faulted"
        stall_marks = [e for e in tr.events if e[1] == "watchdog:stall"]
        assert stall_marks, "stack-dump mark missing"
        assert "stack" in (stall_marks[0][6] or {})
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        s.conf.unset("spark.rapids.tpu.faults.watchdog.stallMs")
        s.conf.unset("spark.rapids.tpu.faults.resubmit.max")
        assert _agg_rows(s, path) == clean  # permit was released
        get_catalog().assert_no_leaks()

    def test_hung_query_resubmitted_then_exhausts(self, gray_session,
                                                  tmp_path):
        s = gray_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=1200, seed=11))
        _agg_rows(s, path)
        s.conf.set("spark.rapids.tpu.faults.watchdog.stallMs", 250.0)
        s.conf.set("spark.rapids.tpu.faults.resubmit.max", 1)
        s.conf.set("spark.rapids.tpu.faults.inject.schedule",
                   "device.hang:1")
        h = s.submit(lambda: _agg_rows(s, path), label="wd-resubmit")
        with pytest.raises(QueryFaulted):
            h.result(timeout=90)
        # the hang re-armed on the retry: faulted -> resubmitted ->
        # faulted, lineage preserved on the one handle
        assert h.resubmits == 1
        assert [a["status"] for a in h.attempts] == ["resubmitted"]
        s.conf.unset("spark.rapids.tpu.faults.inject.schedule")
        s.conf.unset("spark.rapids.tpu.faults.watchdog.stallMs")
        s.conf.unset("spark.rapids.tpu.faults.resubmit.max")
        get_catalog().assert_no_leaks()

    def test_slow_but_alive_query_not_reclaimed(self, gray_session,
                                                tmp_path):
        """Batches keep flowing, each under the window: progress stamps
        hold the watchdog off no matter how long the query runs."""
        s = gray_session
        path = _write_pq(tmp_path, "t.parquet", _frame(n=2000, seed=13))
        clean = _agg_rows(s, path)
        s.conf.set("spark.rapids.tpu.faults.watchdog.stallMs", 400.0)

        def slow_query():
            # batch boundaries pass the checkpoint between sleeps
            rows = _agg_rows(s, path)
            for _ in range(4):
                time.sleep(0.15)  # fault-ok (test pacing, not a retry)
                from spark_rapids_tpu.service import cancel
                cancel.check()
            return rows

        h = s.submit(slow_query, label="wd-slow")
        assert h.result(timeout=60) == clean
        assert h.status == "done"
        s.conf.unset("spark.rapids.tpu.faults.watchdog.stallMs")

    def test_progress_stamped_at_batch_checkpoint(self):
        from spark_rapids_tpu.service import cancel
        ctl = cancel.QueryControl(label="unit")
        assert not ctl.progress_seen
        with cancel.scope(ctl):
            t0 = ctl.progress_t
            time.sleep(0.01)  # fault-ok (test pacing)
            cancel.check()
        assert ctl.progress_seen and ctl.progress_t > t0

    def test_stalled_cancel_raises_query_stalled(self):
        from spark_rapids_tpu.service import cancel
        ctl = cancel.QueryControl(label="unit")
        ctl.cancel("watchdog says stop", stalled=True)
        assert ctl.status == "stalled"
        with pytest.raises(cancel.QueryStalled):
            ctl.raise_()

    def test_semaphore_forfeit_clamps(self):
        from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
        sem = TpuSemaphore(2)
        with sem.acquire():
            assert sem.available() == 1
            sem.forfeit()  # watchdog reclaims the wedged holder
            assert sem.available() == 2
        # the zombie's real release clamped at zero in-use: no
        # phantom third permit
        assert sem.available() == 2


# ---------------------------------------------------------------------------
# Straggler hedging (thread-rank DCN world).
# ---------------------------------------------------------------------------

def _make_group(world, hb_timeout=3.0, spills=None):
    from spark_rapids_tpu.parallel.dcn import Coordinator, ProcessGroup
    coord = Coordinator(world, heartbeat_timeout=hb_timeout,
                        wait_timeout=20.0)
    pgs = [None] * world

    def mk(r):
        pgs[r] = ProcessGroup(r, world, ("127.0.0.1", coord.port),
                              coordinator=coord if r == 0 else None,
                              heartbeat_interval=0.15)

    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert all(pg is not None for pg in pgs)
    return coord, pgs


def _commit_all(shuffles):
    ts = [threading.Thread(target=sh.commit) for sh in shuffles]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)


def _close_all(shuffles):
    ts = [threading.Thread(target=sh.close) for sh in shuffles]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)


class TestStragglerHedging:
    def test_slow_peer_hedged_against_durable(self, fast_backoff,
                                              tmp_path):
        TpuConf.set_session("spark.rapids.tpu.faults.hedge.quantileMs",
                            80.0)
        try:
            from spark_rapids_tpu.parallel.dcn import DcnShuffle
            world, n_parts = 2, 4
            coord, pgs = _make_group(world)
            shuffles = [DcnShuffle(pg, n_parts,
                                   str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            for rank, sh in enumerate(shuffles):
                for p in range(n_parts):
                    sh.write_partition(p, pa.table(
                        {"src": [rank] * 3, "v": list(range(3))}))
            _commit_all(shuffles)
            assert shuffles[0].committed == [0, 1]
            # rank 1's server answers the next fetch LATE (3x the hedge
            # horizon): the hedge must beat it via durable map output
            INJECTOR.arm(schedule="dcn.slow_peer:1")
            s0 = QueryStats.get().snapshot()
            t0 = time.monotonic()
            rows = list(shuffles[0].read_partition(0))
            elapsed = time.monotonic() - t0
            INJECTOR.arm()
            assert sum(t.num_rows for t in rows) == world * 3
            d = QueryStats.delta_since(s0)
            assert d["fragments_hedged"] >= 1
            # first-result-wins: well under the straggler's delay
            assert elapsed < pgs[1]._server.slow_inject_s
            assert 1 in pgs[0].slow_peers  # declared SLOW, not dead
            assert 1 not in pgs[0].dead_peers
            # a fast reply clears the slow state (recoverable, unlike
            # declared-dead): read a partition with the injector off —
            # the immediate hedge races a now-fast fetch; either side
            # winning still notes the response
            list(shuffles[0].read_partition(2))
            _close_all(shuffles)
            for pg in pgs:
                pg.close()
        finally:
            TpuConf.unset_session(
                "spark.rapids.tpu.faults.hedge.quantileMs")
        get_catalog().assert_no_leaks()

    def test_hedge_disabled_keeps_plain_path(self, fast_backoff,
                                             tmp_path):
        TpuConf.set_session("spark.rapids.tpu.faults.hedge.enabled",
                            False)
        try:
            from spark_rapids_tpu.parallel.dcn import DcnShuffle
            coord, pgs = _make_group(2)
            shuffles = [DcnShuffle(pg, 2, str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            for rank, sh in enumerate(shuffles):
                for p in range(2):
                    sh.write_partition(p, pa.table({"src": [rank]}))
            _commit_all(shuffles)
            s0 = QueryStats.get().snapshot()
            rows = list(shuffles[0].read_partition(0))
            assert sum(t.num_rows for t in rows) == 2
            assert QueryStats.delta_since(s0)["fragments_hedged"] == 0
            _close_all(shuffles)
            for pg in pgs:
                pg.close()
        finally:
            TpuConf.unset_session("spark.rapids.tpu.faults.hedge.enabled")


# ---------------------------------------------------------------------------
# Orphan frame GC (the close(delete=False) leftovers from PR 6).
# ---------------------------------------------------------------------------

class TestOrphanFrameGc:
    def test_sweep_removes_old_keeps_fresh(self, tmp_path):
        spill = str(tmp_path)
        old = tmp_path / "shuffle-deadbeef0001"
        old.mkdir()
        (old / "part-00000.bin").write_bytes(b"stale")
        os.utime(old / "part-00000.bin", (1, 1))
        os.utime(old, (1, 1))
        fresh = tmp_path / "shuffle-cafebabe0002"
        fresh.mkdir()
        (fresh / "part-00000.bin").write_bytes(b"live")
        other = tmp_path / "not-a-shuffle"
        other.mkdir()
        assert gc_orphan_frames(spill, 60_000) == 1
        assert not old.exists()
        assert fresh.exists() and other.exists()
        # disabled sweep is a no-op
        os.utime(fresh, (1, 1))
        assert gc_orphan_frames(spill, 0) == 0
        assert fresh.exists()

    def test_new_dcn_shuffle_triggers_sweep(self, fast_backoff,
                                            tmp_path):
        spill = tmp_path / "spill"
        spill.mkdir()
        orphan = spill / "shuffle-00000000dead"
        orphan.mkdir()
        (orphan / "part-00000.bin").write_bytes(b"orphan")
        os.utime(orphan / "part-00000.bin", (1, 1))
        os.utime(orphan, (1, 1))
        TpuConf.set_session(
            "spark.rapids.tpu.faults.dcn.gcOrphanFramesMs", 60_000.0)
        try:
            from spark_rapids_tpu.parallel.dcn import DcnShuffle
            coord, pgs = _make_group(1)
            sh = DcnShuffle(pgs[0], 1, str(spill))
            assert not orphan.exists()  # swept at shuffle start
            assert os.path.isdir(sh.local.dir)  # the live dir is fine
            pgs[0].unregister_shuffle(sh.id)
            sh.local.close()
            pgs[0].close()
        finally:
            TpuConf.unset_session(
                "spark.rapids.tpu.faults.dcn.gcOrphanFramesMs")


# ---------------------------------------------------------------------------
# The mixed chaos differential: gray + fail-stop together.
# ---------------------------------------------------------------------------

class TestMixedChaosDifferential:
    def test_corrupt_fragment_plus_killed_peer(self, fast_backoff,
                                               tmp_path):
        """World=3: rank 2 dies silently mid-shuffle while a surviving
        peer's fragment stream corrupts — survivors' combined result is
        IDENTICAL to the fault-free run, recovery attributable, no
        leaks."""
        from spark_rapids_tpu.parallel.dcn import DcnShuffle
        world, n_parts = 3, 6
        coord, pgs = _make_group(world, hb_timeout=0.6)
        shuffles = []
        try:
            shuffles = [DcnShuffle(pg, n_parts,
                                   str(tmp_path / f"r{pg.rank}"))
                        for pg in pgs]
            for rank, sh in enumerate(shuffles):
                for p in range(n_parts):
                    sh.write_partition(p, pa.table(
                        {"src": [rank] * 2, "part": [p] * 2,
                         "v": [0, 1]}))
            _commit_all(shuffles)
            assert shuffles[0].committed == [0, 1, 2]

            # fail-stop leg: rank 2 dies silently (map output durable)
            pgs[2]._closed = True
            pgs[2]._server.freeze()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not (
                    2 in pgs[0].dead_peers and 2 in pgs[1].dead_peers):
                time.sleep(0.05)  # fault-ok (test poll, not a retry)
            assert 2 in pgs[0].dead_peers and 2 in pgs[1].dead_peers

            # gray leg: the first surviving frame read corrupts
            INJECTOR.arm(schedule="shuffle.corrupt:1")
            s0 = QueryStats.get().snapshot()
            results = {}

            def read_all(rank):
                sh = shuffles[rank]
                rows = []
                for p in sh.my_parts():
                    rows.extend(sh.read_partition(p))
                for p in sh.adopt_orphans():
                    rows.extend(sh.read_partition(p))
                results[rank] = rows

            ts = [threading.Thread(target=read_all, args=(r,))
                  for r in (0, 1)]
            t0 = time.monotonic()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            wall = time.monotonic() - t0
            INJECTOR.arm()
            assert set(results) == {0, 1}
            got = pa.concat_tables(results[0] + results[1])
            # every row all three ranks wrote, exactly once across the
            # two survivors — byte-identical to the fault-free pattern
            assert got.num_rows == world * n_parts * 2
            by = sorted(zip(got.column("src").to_pylist(),
                            got.column("part").to_pylist()))
            assert by == sorted((r, p) for r in range(world)
                                for p in range(n_parts)
                                for _ in range(2))
            d = QueryStats.delta_since(s0)
            # both failure modes were DETECTED and healed
            assert d["integrity_failures"] >= 1          # gray
            assert d["fragments_recomputed"] >= 1        # corrupt re-pull
            assert d["fragments_recomputed_remote"] >= 1  # dead re-pull
            assert d["partitions_reowned"] >= 1           # adoption
            assert wall < 30  # bounded, nowhere near waitTimeout
            # survivors retire the shuffle collectively (the close
            # barrier completes over the ALIVE membership)
            _close_all(shuffles[:2])
            shuffles = [shuffles[2]]
        finally:
            for sh in shuffles:
                sh.local.close()
            for pg in pgs:
                pg.close()
        get_catalog().assert_no_leaks()


# ---------------------------------------------------------------------------
# trace_report: integrity:/stalls: summary lines.
# ---------------------------------------------------------------------------

class TestTraceReportGray:
    def test_summary_lines_render(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        from tools.trace_report import analyze, format_report
        data = {
            "traceEvents": [
                {"ph": "X", "cat": "query", "name": "q", "ts": 0.0,
                 "dur": 1000.0, "pid": 1, "tid": 0,
                 "args": {"integrity_failures": 2, "fragments_hedged": 1,
                          "stalls_detected": 1}},
                {"ph": "X", "cat": "fault", "name": "peer:slow",
                 "ts": 1.0, "dur": 0.0, "pid": 1, "tid": 1,
                 "args": {"rank": 1}},
            ],
            "spanTree": [],
            "otherData": {"label": "gray-q", "status": "ok"},
        }
        a = analyze(data)
        assert a["integrity_failures"] == 2
        assert a["fragments_hedged"] == 1
        assert a["stalls_detected"] == 1
        assert a["peers_slow"] == 1
        report = format_report(a)
        assert "integrity: failures=2 hedged=1 slow_peers=1" in report
        assert "stalls: detected=1" in report

    def test_clean_trace_omits_gray_lines(self):
        from tools.trace_report import analyze, format_report
        data = {"traceEvents": [
            {"ph": "X", "cat": "query", "name": "q", "ts": 0.0,
             "dur": 100.0, "pid": 1, "tid": 0, "args": {}}],
            "spanTree": [], "otherData": {"label": "clean"}}
        report = format_report(analyze(data))
        assert "integrity:" not in report
        assert "stalls:" not in report

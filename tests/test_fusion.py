"""Whole-query data-path fusion (plan/fusion.py).

Region formation over the streaming spine, the maxOps splitter, stage
merging, the fingerprint contract (region programs keyed by the member
chain; cached DATA keyed see-through so fusion on/off share entries),
and the RegionPrologue batching object behind the single prologue
fetch.  End-to-end sync-budget differentials live in
tests/test_sync_budget.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.cache.keys import plan_fingerprint
from spark_rapids_tpu.plan.coalesce import CoalesceBatchesExec
from spark_rapids_tpu.plan.fusion import (FusedRegionExec, _merge_stages,
                                          _split_chain, note_self_time,
                                          plan_regions, region_fingerprint)
from spark_rapids_tpu.plan.overrides import apply_overrides
from spark_rapids_tpu.plan.physical import ScanExec, StageExec, TpuExec
from spark_rapids_tpu.plan.planner import explain_regions, plan_query_regions
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils.metrics import (QueryStats, RegionPrologue,
                                            current_region, region_fetch,
                                            region_scalars, region_scope,
                                            stage_scalars)

F = srt.functions


@pytest.fixture()
def sess():
    return srt.Session.get_or_create()


def _find(phys, cls):
    out, stack = [], [phys]
    while stack:
        n = stack.pop()
        if isinstance(n, cls):
            out.append(n)
        stack.extend(n.children)
    return out


def _plan(sess, q, **conf):
    for k, v in conf.items():
        sess.conf.set(k, v)
    try:
        return apply_overrides(q._plan, sess._tpu_conf())
    finally:
        for k in conf:
            sess.conf.unset(k)


def _chain_query(sess, n=4096):
    rng = np.random.default_rng(5)
    df = sess.create_dataframe({
        "a": rng.integers(0, 100, n).astype(np.int64),
        "b": rng.random(n)})
    return (df.filter(F.col("a") < 50)
              .with_column("c", F.col("b") * 2)
              .agg(F.sum(F.col("c")).alias("s")))


def _join_query(sess, n=8192):
    rng = np.random.default_rng(6)
    fact = sess.create_dataframe({
        "k": rng.integers(0, 256, n).astype(np.int64),
        "j": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.random(n)})
    d1 = sess.create_dataframe({"k": np.arange(256, dtype=np.int64),
                                "w": rng.random(256)})
    d2 = sess.create_dataframe({"j": np.arange(64, dtype=np.int64),
                                "u": rng.random(64)})
    return (fact.filter(F.col("k") < 200)
                .join(d1, "k", "inner").join(d2, "j", "inner")
                .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))


class TestRegionPlanner:
    def test_chain_forms_one_region(self, sess):
        phys = _plan(sess, _chain_query(sess))
        regions = _find(phys, FusedRegionExec)
        assert len(regions) == 1
        names = [type(m).__name__ for m in regions[0].members]
        assert names[0] == "AggregateExec" and names[-1] == "ScanExec"
        # the member subtree stays intact under the wrapper (EXPLAIN /
        # trace attribution): children[0] IS the chain head
        assert regions[0].children[0] is regions[0].members[0]

    def test_escape_hatch_produces_identical_plan(self, sess):
        from spark_rapids_tpu.config import TpuConf
        q = _chain_query(sess)
        off = _plan(sess, q, **{"spark.rapids.tpu.sql.fusion.enabled": False})
        assert _find(off, FusedRegionExec) == []
        # plan_regions with fusion disabled is the identity function:
        # the escape hatch returns the very same tree object
        conf_off = TpuConf({"spark.rapids.tpu.sql.fusion.enabled": False})
        assert plan_regions(off, conf_off) is off

    def test_join_spine_keeps_build_side_out(self, sess):
        """The region follows the streaming (probe) spine; the broadcast
        build side stays outside so its exchange/materialize semantics
        are untouched."""
        phys = _plan(sess, _join_query(sess))
        regions = _find(phys, FusedRegionExec)
        assert regions, "join chain should fuse"
        names = [type(m).__name__ for m in regions[0].members]
        assert names.count("BroadcastJoinExec") == 2
        # both dim-table scans live OUTSIDE the region members
        member_ids = {id(m) for r in regions for m in r.members}
        scans = _find(phys, ScanExec)
        outside = [s for s in scans if id(s) not in member_ids]
        assert len(outside) >= 2

    def test_max_ops_splits_regions(self, sess):
        phys = _plan(sess, _join_query(sess),
                     **{"spark.rapids.tpu.sql.fusion.maxOps": 2})
        regions = _find(phys, FusedRegionExec)
        assert regions
        assert all(len(r.members) <= 2 for r in regions)

    def test_split_chain_cuts_at_cheapest_boundary(self):
        """The splitter cuts where adjacent observed self-times are
        smallest (least dispatch overhead saved by keeping them fused)."""
        class _N:
            region_fusible = True

            def __init__(self, tag):
                self.tag = tag

            def fingerprint(self):
                return f"split-test-{self.tag}"

        nodes = [_N(i) for i in range(4)]
        for n, t in zip(nodes, (5.0, 5.0, 0.001, 0.001)):
            from spark_rapids_tpu.plan.fusion import _member_key
            note_self_time(_member_key(n), t)
        segs = _split_chain(nodes, 3)
        assert [len(s) for s in segs] == [2, 2]

    def test_explain_regions_lines(self, sess):
        phys = _plan(sess, _chain_query(sess))
        lines = explain_regions(phys)
        assert len(lines) == 1
        assert lines[0].startswith("region[")
        assert "ScanExec" in lines[0]
        assert explain_regions(
            _plan(sess, _chain_query(sess),
                  **{"spark.rapids.tpu.sql.fusion.enabled": False})) == []

    def test_plan_query_regions_delegates(self, sess):
        off = _plan(sess, _chain_query(sess),
                    **{"spark.rapids.tpu.sql.fusion.enabled": False})
        on = plan_query_regions(off, sess._tpu_conf())
        assert _find(on, FusedRegionExec)


class TestStageMerge:
    def test_merge_stages_concatenates_programs(self, sess):
        """Splitting a planned stage in two and merging back yields the
        same steps, child, and traced-program fingerprint."""
        off = _plan(sess, _chain_query(sess),
                    **{"spark.rapids.tpu.sql.fusion.enabled": False})
        st = _find(off, StageExec)[0]
        assert len(st.steps) >= 2 and not st.host_exprs
        scan = st.children[0]
        # cut after the leading filter: the intermediate schema there is
        # still the scan schema, so both halves bind correctly
        assert st.steps[0][0] == "filter"

        def mk(child, steps, schema):
            s = StageExec.__new__(StageExec)
            TpuExec.__init__(s, [child])
            s.steps, s.host_exprs, s._schema = list(steps), [], schema
            return s

        bottom = mk(scan, st.steps[:1], scan.output_schema)
        top = mk(bottom, st.steps[1:], st.output_schema)
        merged = _merge_stages(top, bottom)
        assert merged.steps == st.steps
        assert merged.children[0] is scan
        assert merged.output_schema is st.output_schema
        assert merged.fingerprint() == st.fingerprint()


class TestFingerprints:
    def test_region_fingerprint_chains_members(self, sess):
        phys = _plan(sess, _chain_query(sess))
        r = _find(phys, FusedRegionExec)[0]
        fp = region_fingerprint(r)
        assert fp != region_fingerprint(
            _find(_plan(sess, _join_query(sess)), FusedRegionExec)[0])

    def test_plan_fingerprint_sees_through_regions(self, sess):
        """Cached DATA is keyed by what was computed, not by how it was
        grouped: fusion on/off must share query-cache entries."""
        phys = _plan(sess, _chain_query(sess))
        r = _find(phys, FusedRegionExec)[0]
        assert plan_fingerprint(r) == plan_fingerprint(r.children[0])


class TestRegionPrologue:
    def _stats(self):
        st = QueryStats()
        tok = M._STATS_STACK.set(M._STATS_STACK.get() + (st,))
        return st, tok

    def test_resolve_batches_staged_vectors(self):
        """N staged stat vectors resolve in ONE blocking fetch."""
        st, tok = self._stats()
        try:
            r = RegionPrologue("region@test")
            r.stage("a", jnp.arange(4))
            r.stage("b", jnp.arange(8) * 2)
            before = st.blocking_fetches
            va = r.scalars("a", jnp.arange(4))
            vb = r.scalars("b", jnp.arange(8) * 2)
            assert va == [0, 1, 2, 3]
            assert vb[:2] == [0, 2]
            assert st.blocking_fetches == before + 1
            assert st.region_fetches == 1
        finally:
            M._STATS_STACK.reset(tok)

    def test_region_scope_and_fallbacks(self):
        assert current_region() is None
        # outside any region the helpers degrade to plain fetches
        assert region_scalars(jnp.asarray([7]))[0] == 7
        assert int(np.asarray(region_fetch(jnp.asarray([9])))[0]) == 9
        with region_scope("region@scope") as r:
            assert current_region() is r
            stage_scalars("k", jnp.asarray([1, 2]))
            assert region_scalars(jnp.asarray([1, 2]), key="k") == [1, 2]
        assert current_region() is None

    def test_anonymous_keys_are_distinct(self):
        with region_scope("region@anon"):
            a = region_fetch(jnp.asarray([1]))
            b = region_fetch(jnp.asarray([2]))
        assert int(np.asarray(a)[0]) == 1
        assert int(np.asarray(b)[0]) == 2


class TestExecution:
    def test_region_is_single_pipeline_stage(self, sess):
        """effective_depth collapses to 0 inside a region: members pull
        serially; only the region's consumer keeps configured depth."""
        from spark_rapids_tpu.plan.physical import ExecContext
        from spark_rapids_tpu.runtime.pipeline import effective_depth
        ctx = ExecContext(sess._tpu_conf().with_settings(
            **{"spark.rapids.tpu.sql.pipeline.depth": 2}),
            device=sess.device)
        assert effective_depth(ctx) == 2
        with region_scope("region@depth"):
            assert effective_depth(ctx) == 0
        assert effective_depth(ctx) == 2

    def test_fused_execution_matches_unfused(self, sess):
        q = _join_query(sess)

        def run(fusion):
            sess.conf.set("spark.rapids.tpu.sql.fusion.enabled", fusion)
            st = QueryStats()
            tok = M._STATS_STACK.set(M._STATS_STACK.get() + (st,))
            try:
                return q.collect(), st
            finally:
                M._STATS_STACK.reset(tok)
                sess.conf.unset("spark.rapids.tpu.sql.fusion.enabled")

        on, s_on = run(True)
        off, s_off = run(False)
        assert s_on.fused_regions >= 1
        assert s_off.fused_regions == 0

        def norm(rows):
            return sorted(tuple(r.values()) if isinstance(r, dict)
                          else tuple(r) for r in rows)

        assert norm(on) == norm(off)

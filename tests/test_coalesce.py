"""Coalesce goal algebra + CoalesceBatchesExec transition pass.

Reference: GpuCoalesceBatches.scala:159-192 (TargetSize/RequireSingleBatch
goal algebra) and GpuTransitionOverrides inserting coalesce nodes before
per-batch-sensitive operators.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.plan.coalesce import (CoalesceBatchesExec,
                                            RequireSingleBatch, TargetSize,
                                            max_goal)
from spark_rapids_tpu.plan.overrides import apply_overrides
from spark_rapids_tpu.sql import functions as F


def _find(phys, cls):
    out = []
    stack = [phys]
    while stack:
        n = stack.pop()
        if isinstance(n, cls):
            out.append(n)
        stack.extend(n.children)
    return out


class TestGoalAlgebra:
    def test_max_goal(self):
        assert max_goal(None, TargetSize(10)) == TargetSize(10)
        assert max_goal(TargetSize(10), TargetSize(99)) == TargetSize(99)
        assert max_goal(TargetSize(10), RequireSingleBatch) \
            is RequireSingleBatch
        assert max_goal(None, None) is None

    def test_satisfied_by(self):
        assert TargetSize(100).satisfied_by(100, False)
        assert not TargetSize(100).satisfied_by(99, False)
        assert RequireSingleBatch.satisfied_by(5, True)
        assert not RequireSingleBatch.satisfied_by(5, False)


class TestCoalesceExec:
    def _scan(self, session, tables):
        from spark_rapids_tpu.batch import Field, Schema, _arrow_to_logical
        from spark_rapids_tpu.plan.physical import ScanExec
        schema = Schema([Field(n, _arrow_to_logical(t), True)
                         for n, t in zip(tables[0].column_names,
                                         tables[0].schema.types)])
        return ScanExec(schema, lambda: iter(tables), desc="test")

    def _run(self, session, exec_):
        from spark_rapids_tpu.plan.physical import ExecContext
        ctx = ExecContext(session._tpu_conf(), device=session.device)
        return list(exec_.execute(ctx))

    def test_target_size_merges_small_batches(self, session):
        tables = [pa.table({"v": np.arange(i * 10, i * 10 + 10)})
                  for i in range(10)]  # 10 batches x 10 rows
        co = CoalesceBatchesExec(self._scan(session, tables),
                                 TargetSize(30))
        outs = self._run(session, co)
        assert [b.num_rows for b in outs] == [30, 30, 30, 10]
        got = [v for b in outs
               for v in np.asarray(b.columns[0].data)[:b.num_rows].tolist()]
        assert got == list(range(100))

    def test_large_batch_passes_through(self, session):
        tables = [pa.table({"v": np.arange(100)}),
                  pa.table({"v": np.arange(5)})]
        co = CoalesceBatchesExec(self._scan(session, tables),
                                 TargetSize(50))
        outs = self._run(session, co)
        assert [b.num_rows for b in outs] == [100, 5]

    def test_large_batch_flushes_pending_first(self, session):
        """A big dense batch never pays a merge sort for stray small rows
        queued ahead of it — pending flushes, then it passes through."""
        tables = [pa.table({"v": np.arange(10)}),
                  pa.table({"v": np.arange(10, 110)})]
        co = CoalesceBatchesExec(self._scan(session, tables),
                                 TargetSize(50))
        outs = self._run(session, co)
        assert [b.num_rows for b in outs] == [10, 100]
        got = [v for b in outs
               for v in np.asarray(b.columns[0].data)[:b.num_rows].tolist()]
        assert got == list(range(110))

    def test_masked_batches_merge_and_compact(self, session):
        """Masked batches accumulate WITHOUT per-batch host syncs: live
        counts stay device scalars until a capacity-threshold 'look'
        resolves them all in one fetch, and the flush compacts the merge
        to the true live total."""
        import jax.numpy as jnp

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.batch import (ColumnBatch, DeviceColumn,
                                            Field, Schema)
        from spark_rapids_tpu.plan.physical import TpuExec
        schema = Schema([Field("v", T.INT64, False)])

        def masked(lo, n_live, cap=8):
            data = jnp.arange(lo, lo + cap, dtype=jnp.int64)
            sel = jnp.arange(cap) < n_live
            return ColumnBatch(schema, [DeviceColumn(T.INT64, data)],
                               cap, sel)

        class Src(TpuExec):
            output_schema = schema

            def execute(self, ctx):
                yield masked(0, 5)
                yield masked(100, 5)
                yield masked(200, 5)

        co = CoalesceBatchesExec(Src(), TargetSize(12))
        outs = self._run(session, co)
        # look threshold = 2x goal = 24 capacity: the third batch trips
        # it, the resolved live total (15) satisfies the goal -> ONE
        # merged batch of 15 live rows
        assert [b.num_rows for b in outs] == [15]
        got = sorted(np.asarray(outs[0].columns[0].data)[:15].tolist())
        assert got == list(range(0, 5)) + list(range(100, 105)) \
            + list(range(200, 205))

    def test_stacked_goals_combine(self, session):
        from spark_rapids_tpu.plan.coalesce import insert_coalesce
        from spark_rapids_tpu.plan.physical import ScanExec
        scan = self._scan(session, [pa.table({"v": np.arange(5)})])
        inner = CoalesceBatchesExec(scan, TargetSize(10))

        class Outer:
            def __init__(self, child):
                self.children = [child]

            def child_coalesce_goal(self, i, conf):
                return RequireSingleBatch

        conf = session._tpu_conf()
        out = Outer(inner)
        insert_coalesce(out, conf)
        assert out.children[0] is inner
        assert inner.goal is RequireSingleBatch

    def test_require_single_batch(self, session):
        tables = [pa.table({"v": np.arange(7)}) for _ in range(5)]
        co = CoalesceBatchesExec(self._scan(session, tables),
                                 RequireSingleBatch)
        outs = self._run(session, co)
        assert [b.num_rows for b in outs] == [35]


class TestTransitionPass:
    def test_agg_and_sort_get_target_goals(self, session):
        df = session.create_dataframe({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
        q = df.group_by("k").agg(F.sum(F.col("v")).alias("s")).sort("k")
        phys = apply_overrides(q._plan, df.session._tpu_conf())
        cos = _find(phys, CoalesceBatchesExec)
        # partial agg input + sort input get goals; the final agg's
        # exchange child is partition-aligned and must NOT be coalesced
        assert len(cos) >= 1
        from spark_rapids_tpu.plan.exchange_exec import ShuffleExchangeExec
        for co in cos:
            assert not isinstance(co.children[0], ShuffleExchangeExec)

    def test_window_gets_single_batch_goal(self, session):
        from spark_rapids_tpu.sql.window import Window
        df = session.create_dataframe({"k": [1, 1, 2], "v": [3.0, 1.0, 2.0]})
        w = Window.partition_by("k").order_by("v")
        q = df.select(F.col("k"), F.row_number().over(w).alias("rn"))
        phys = apply_overrides(q._plan, df.session._tpu_conf())
        goals = [c.goal for c in _find(phys, CoalesceBatchesExec)]
        assert RequireSingleBatch in goals

    def test_disabled_by_config(self, session):
        import spark_rapids_tpu as srt_
        srt_.Session.reset()
        s = srt_.Session.get_or_create(settings={
            "spark.rapids.tpu.sql.coalesce.enabled": False})
        try:
            df = s.create_dataframe({"k": [1], "v": [1.0]})
            q = df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
            phys = apply_overrides(q._plan, s._tpu_conf())
            assert not _find(phys, CoalesceBatchesExec)
        finally:
            srt_.Session.reset()

    def test_many_small_files_coalesce_correct(self, tmp_path, session):
        rng = np.random.default_rng(3)
        frames = []
        for i in range(6):
            t = pa.table({"k": rng.integers(0, 5, 40),
                          "v": rng.normal(size=40)})
            pq.write_table(t, str(tmp_path / f"f{i}.parquet"))
            frames.append(t)
        whole = pa.concat_tables(frames)
        sess = srt.Session.get_or_create()
        df = sess.read_parquet(str(tmp_path))
        got = sorted(df.group_by("k").agg(F.sum(F.col("v")).alias("s"))
                     .collect())
        import collections
        expect = collections.defaultdict(float)
        for k, v in zip(whole.column("k").to_pylist(),
                        whole.column("v").to_pylist()):
            expect[k] += v
        for (k, s) in got:
            assert s == pytest.approx(expect[k], rel=1e-12)

"""Pipelined async executor (runtime/pipeline.py) regression tests.

Three contracts the pipeline must never break:
  (a) pipelined (depth>0) and serial (depth=0) execution produce
      identical results — the pipeline reorders WHEN work happens,
      never WHAT is computed;
  (b) depth is a hard bound on staged batches (HBM stays bounded);
  (c) buffer donation only ever sees single-consumer batches — a batch
      referenced by a SpillableBatch handle or the device-tier file
      cache is never donatable.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.runtime.pipeline import (donation_supported,
                                               effective_depth,
                                               pipeline_batches,
                                               pipeline_map)

# sync-heavy + scan-heavy representatives (q13/q16 are the PERF.md deep
# losers this pipeline targets; q1/q6 cover the fused-agg scan path)
SLICE = ["q1", "q3", "q6", "q13", "q16"]


# ---------------------------------------------------------------------------
# (a) pipelined == serial, byte for byte
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch(session, tmp_path_factory):
    from spark_rapids_tpu.models import tpch_suite
    out = str(tmp_path_factory.mktemp("tpch_pipeline"))
    return tpch_suite.load_db(session, 0.002, out)


@pytest.mark.parametrize("name", SLICE)
def test_pipelined_matches_serial_tpch(session, tpch, name):
    from spark_rapids_tpu.models import tpch_suite
    runner, _ = tpch_suite.QUERIES[name]
    results = {}
    for depth in (0, 2):
        session.conf.set("spark.rapids.tpu.sql.pipeline.depth", depth)
        try:
            results[depth] = runner(tpch)
        finally:
            session.conf.unset("spark.rapids.tpu.sql.pipeline.depth")
    assert results[0] == results[2], \
        f"{name}: depth=2 diverged from serial depth=0"


def test_pipelined_matches_serial_multibatch(session):
    """Small batches force a long pipeline (many staged uploads) through
    scan→filter→project→grouped agg→sort."""
    f = srt.functions
    rng = np.random.default_rng(11)
    df = session.create_dataframe({
        "k": rng.integers(0, 37, 20000).astype(np.int64),
        "v": rng.random(20000)})
    q = (df.filter(f.col("v") > 0.25)
           .select(f.col("k"), (f.col("v") * 3.0).alias("w"))
           .group_by("k").agg(f.sum(f.col("w")).alias("sw"))
           .sort(f.col("k")))
    out = {}
    for depth in (0, 3):
        session.conf.set("spark.rapids.tpu.sql.pipeline.depth", depth)
        session.conf.set("spark.rapids.tpu.sql.batchSizeRows", 2048)
        try:
            out[depth] = q.collect()
        finally:
            session.conf.unset("spark.rapids.tpu.sql.pipeline.depth")
            session.conf.unset("spark.rapids.tpu.sql.batchSizeRows")
    assert out[0] == out[3]


# ---------------------------------------------------------------------------
# (b) depth bounds
# ---------------------------------------------------------------------------

def test_pipeline_depth_bound():
    """At most `depth` staged items are ever live: a slot is reserved
    before the worker produces, so queue + in-flight <= depth."""
    lock = threading.Lock()
    staged = []
    peak = [0]

    def stage(i):
        with lock:
            staged.append(i)
            peak[0] = max(peak[0], len(staged))
        return i

    consumed = []
    for x in pipeline_map(range(50), stage, depth=2):
        with lock:
            staged.remove(x)  # delivered: no longer staged
        # let the worker run ahead as far as it can while we "compute"
        time.sleep(0.002)
        consumed.append(x)
    assert consumed == list(range(50))  # order preserved
    assert 1 <= peak[0] <= 2, f"staged-ahead peak {peak[0]} exceeds depth"


def test_pipeline_depth_zero_is_synchronous():
    """depth=0 must not spawn a worker: production interleaves strictly
    with consumption (the escape-hatch semantics)."""
    trace = []

    def gen():
        for i in range(4):
            trace.append(("produce", i))
            yield i

    for x in pipeline_map(gen(), lambda i: i, depth=0):
        trace.append(("consume", x))
    assert trace == [("produce", 0), ("consume", 0),
                     ("produce", 1), ("consume", 1),
                     ("produce", 2), ("consume", 2),
                     ("produce", 3), ("consume", 3)]


def test_pipeline_propagates_errors_and_stops():
    def gen():
        yield 1
        raise ValueError("upstream boom")

    it = pipeline_batches(gen(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="upstream boom"):
        next(it)


def test_pipeline_abandon_closes_upstream():
    """A consumer that stops early (LIMIT) must close the upstream
    generator instead of leaking the worker + staged batches."""
    closed = threading.Event()

    def gen():
        try:
            for i in range(1000):
                yield i
        finally:
            closed.set()

    it = pipeline_batches(gen(), depth=2)
    assert next(it) == 0
    it.close()
    assert closed.wait(timeout=5.0), "upstream generator never closed"


def test_effective_depth_resolution(session):
    """OOM-injection runs disable pipelining (deterministic injection
    points need a single thread issuing device ops); on the CPU backend
    the unset default resolves to serial (same-silicon overlap is pure
    contention) while an explicit depth always wins."""
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plan.physical import ExecContext
    ctx = ExecContext()
    try:
        # unset on the CPU test backend: backend-aware default = serial
        assert effective_depth(ctx) == 0
        # explicitly set: honored verbatim
        ctx_set = ExecContext(TpuConf(
            {"spark.rapids.tpu.sql.pipeline.depth": 3}))
        assert effective_depth(ctx_set) == 3
        # OOM injection armed: forced serial even when explicitly set
        ctx_inj = ExecContext(ctx_set.conf.with_settings(**{
            "spark.rapids.tpu.test.injectRetryOOM": 1}))
        assert effective_depth(ctx_inj) == 0
    finally:
        # disarm: ExecContext arms the process-global OOM injector
        ExecContext(ctx.conf)


# ---------------------------------------------------------------------------
# (c) donation eligibility
# ---------------------------------------------------------------------------

def _scan_exec(table, **conf):
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plan.physical import ExecContext, ScanExec
    from spark_rapids_tpu.batch import Schema, Field, _arrow_to_logical
    schema = Schema([Field(n, _arrow_to_logical(t), True)
                     for n, t in zip(table.column_names,
                                     table.schema.types)])
    scan = ScanExec(schema, lambda: iter([table]), desc="mem")
    return scan, ExecContext(TpuConf(conf))


def _table(n=4096):
    rng = np.random.default_rng(5)
    return pa.table({"a": rng.integers(0, 100, n),
                     "b": rng.random(n)})


def test_fresh_scan_batches_are_donatable(session):
    scan, ctx = _scan_exec(_table())
    batches = list(scan.execute(ctx))
    assert batches and all(b.donatable for b in batches)


def test_spill_registration_clears_donatable(session):
    from spark_rapids_tpu.memory.spill import SpillCatalog
    scan, ctx = _scan_exec(_table())
    b = next(scan.execute(ctx))
    assert b.donatable
    cat = SpillCatalog(1 << 30, 1 << 30)
    h = cat.register(b)
    try:
        # the handle is a second reference: donating b's buffers to a
        # stage program would corrupt what h.get() re-materializes
        assert not b.donatable
    finally:
        h.close()


def test_device_cached_scan_batches_not_donatable(session, tmp_path):
    """Both the populate-path re-wraps and later cache hits share the
    cached arrays — neither may ever be donated."""
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io.filecache import clear_device_cache
    from spark_rapids_tpu.io.parquet import ParquetSource
    path = str(tmp_path / "t.parquet")
    pq.write_table(_table(), path)
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plan.physical import ExecContext, ScanExec
    clear_device_cache()
    src = ParquetSource(path)
    scan = ScanExec(src.schema(), src, desc="pq")
    conf = {"spark.rapids.tpu.sql.fileCache.enabled": True,
            "spark.rapids.tpu.sql.fileCache.deviceTier": True}
    first = list(scan.execute(ExecContext(TpuConf(conf))))
    hits = list(scan.execute(ExecContext(TpuConf(conf))))
    clear_device_cache()
    assert first and hits
    assert all(not b.donatable for b in first)
    assert all(not b.donatable for b in hits)


def test_stage_output_donatable_and_correct(session):
    """Stage outputs are fresh program results (donatable downstream);
    donation itself only engages off-CPU, so on the test backend the
    non-donating program must produce the same rows."""
    f = srt.functions
    df = session.create_dataframe(
        {"x": np.arange(100, dtype=np.int64)})
    rows = (df.filter(f.col("x") % 2 == 0)
              .select((f.col("x") * 10).alias("y")).collect())
    assert sorted(r[0] for r in rows) == [x * 10 for x in range(0, 100, 2)]
    assert not donation_supported()  # CPU test backend: donation is a no-op

"""On-device smoke subset (round-2 verdict weak #10).

Run on the REAL chip with ``SRT_TESTS_ON_TPU=1 pytest -m tpu_smoke``;
under the default virtual-CPU conftest these run too (they are fast), so
the marker set can never rot.  Covers the regimes where the CPU platform
hides real-device bugs: x64 semantics, padding/capacity buckets, grid
aggregation, join gathers, window sorts.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.window import Window

pytestmark = pytest.mark.tpu_smoke


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def test_scan_filter_agg_f64_exact(sess, rng):
    n = 50_000
    t = pa.table({"v": pa.array(rng.uniform(0, 1e9, n)),
                  "d": pa.array(rng.integers(0, 11, n) / 100.0)})
    got = (sess.create_dataframe(t)
           .where((F.col("d") >= 0.05) & (F.col("d") <= 0.07))
           .agg(F.sum(F.col("v") * F.col("d")).alias("s"))).collect()[0][0]
    pdf = t.to_pandas()
    m = (pdf.d >= 0.05) & (pdf.d <= 0.07)
    want = float((pdf.v[m] * pdf.d[m]).sum())
    assert abs(got - want) <= 1e-9 * abs(want)


def test_grouped_agg_grid_and_sort_paths(sess, rng):
    n = 20_000
    t = pa.table({"k": pa.array([["A", "B", "C"][i % 3]
                                 for i in range(n)]),
                  "hk": pa.array(rng.integers(0, 5000, n)),
                  "v": pa.array(rng.integers(-100, 100, n)
                                .astype(np.int64))})
    df = sess.create_dataframe(t)
    grid = {r[0]: r[1] for r in
            df.group_by("k").agg(F.sum(F.col("v")).alias("s")).collect()}
    pdf = t.to_pandas()
    for k, g in pdf.groupby("k"):
        assert grid[k] == int(g.v.sum())
    srt = {r[0]: r[1] for r in
           df.group_by("hk").agg(F.count_star().alias("c")).collect()}
    assert sum(srt.values()) == n


def test_join_and_window(sess, rng):
    n = 5000
    fact = pa.table({"k": pa.array(rng.integers(0, 50, n)),
                     "v": pa.array(rng.uniform(0, 100, n))})
    dim = pa.table({"k": pa.array(np.arange(50, dtype=np.int64)),
                    "w": pa.array(np.arange(50, dtype=np.int64) * 2)})
    df = (sess.create_dataframe(fact)
          .join(sess.create_dataframe(dim), on="k")
          .select(F.col("k"), F.col("v"), F.col("w")))
    rows = df.collect()
    assert len(rows) == n
    assert all(r[2] == r[0] * 2 for r in rows)
    w = Window.partition_by("k").order_by("v")
    rn = df.select(F.col("k"), F.row_number().over(w).alias("rn")).collect()
    pdf = fact.to_pandas()
    counts = pdf.groupby("k").size()
    got_max = {}
    for k, r in rn:
        got_max[k] = max(got_max.get(k, 0), r)
    for k, c in counts.items():
        assert got_max[k] == c

"""Join scale + condition breadth (round-2 verdict item 6).

Sub-partitioning: an oversized shuffled partition pair (skew: one hot
key) re-partitions by a second independent hash and joins sub-pairs —
GpuSubPartitionHashJoin.scala analog, spark.rapids.tpu.sql.join.
subPartitions.  Conditions: residual (non-equi) conditions participate in
MATCHING for left/semi/anti joins (GpuHashJoin.scala conditional joins),
on the device path.
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


THRESH = "spark.rapids.tpu.sql.autoBroadcastJoinThreshold"


def _brute_join(lt, rt, lk, rk, how, cond=None):
    """Python oracle with pair-level conditions."""
    lrows = [tuple(c[i] for c in lt.columns) for i in
             range(lt.num_rows)]
    rrows = [tuple(c[i] for c in rt.columns) for i in range(rt.num_rows)]
    lnames = lt.column_names
    rnames = rt.column_names
    li = lnames.index(lk)
    ri = rnames.index(rk)
    out = []
    for lr in lrows:
        lrp = tuple(x.as_py() if hasattr(x, "as_py") else x for x in lr)
        matches = []
        for rr in rrows:
            rrp = tuple(x.as_py() if hasattr(x, "as_py") else x
                        for x in rr)
            if lrp[li] is None or rrp[ri] is None or lrp[li] != rrp[ri]:
                continue
            if cond is not None and not cond(dict(zip(lnames, lrp)),
                                             dict(zip(rnames, rrp))):
                continue
            matches.append(rrp)
        if how == "inner":
            out += [lrp + m for m in matches]
        elif how == "left":
            out += ([lrp + m for m in matches] if matches
                    else [lrp + (None,) * len(rnames)])
        elif how == "semi":
            if matches:
                out.append(lrp)
        elif how == "anti":
            if not matches:
                out.append(lrp)
        elif how in ("right", "full"):
            out += [lrp + m for m in matches]
            if how == "full" and not matches:
                out.append(lrp + (None,) * len(rnames))
    if how in ("right", "full"):
        # unmatched RIGHT rows null-pad the left side
        for rr in rrows:
            rrp = tuple(x.as_py() if hasattr(x, "as_py") else x
                        for x in rr)
            matched = False
            for lr in lrows:
                lrp = tuple(x.as_py() if hasattr(x, "as_py") else x
                            for x in lr)
                if lrp[li] is None or rrp[ri] is None \
                        or lrp[li] != rrp[ri]:
                    continue
                if cond is not None and not cond(
                        dict(zip(lnames, lrp)), dict(zip(rnames, rrp))):
                    continue
                matched = True
                break
            if not matched:
                out.append((None,) * len(lnames) + rrp)
    return sorted(out, key=lambda r: tuple((x is None, str(x)) for x in r))


class TestSubPartitioning:
    def test_skewed_hot_key_completes_and_matches(self, sess, rng):
        """One hot key dominating the batch: the pair exceeds
        batchSizeRows and sub-partitions; results must match the
        unsplit plan exactly."""
        n = 4000
        hot = np.zeros(n // 2, dtype=np.int64)  # one hot key = half the rows
        cold = rng.integers(1, 500, n - n // 2)
        lt = pa.table({"k": np.concatenate([hot, cold]),
                       "a": np.arange(n, dtype=np.int64)})
        rt = pa.table({"k": pa.array(np.arange(0, 500, dtype=np.int64)),
                       "b": pa.array(np.arange(500, dtype=np.int64) * 10)})
        sess.conf.set(THRESH, -1)  # force the shuffled path
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1024)
        try:
            df = sess.create_dataframe(lt).join(
                sess.create_dataframe(rt), on="k", how="inner")
            phys = sess._plan_physical(df._plan)
            ctx_rows = sorted(df.collect())
            # oracle: same join without the sub-partition trigger
            sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1 << 22)
            want = sorted(df.collect())
            assert ctx_rows == want
            assert len(ctx_rows) == n  # every left row matches exactly once
        finally:
            sess.conf.unset("spark.rapids.tpu.sql.batchSizeRows")
            sess.conf.set(THRESH, 10 * 1024 * 1024)

    def test_subpartition_metric_fires(self, sess, rng):
        from spark_rapids_tpu.plan.physical import CollectExec, ExecContext
        n = 3000
        lt = pa.table({"k": rng.integers(0, 7, n),
                       "a": np.arange(n, dtype=np.int64)})
        rt = pa.table({"k": pa.array(np.arange(7, dtype=np.int64)),
                       "b": pa.array(np.arange(7, dtype=np.int64))})
        sess.conf.set(THRESH, -1)
        sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 256)
        try:
            df = sess.create_dataframe(lt).join(
                sess.create_dataframe(rt), on="k")
            phys = sess._plan_physical(df._plan)
            ctx = ExecContext(sess._tpu_conf(), device=sess.device)
            CollectExec(phys).collect_arrow(ctx)
            fired = sum(ms.values.get("subPartitionedPairs", 0)
                        for ms in ctx.metrics.values())
            assert fired > 0
        finally:
            sess.conf.unset("spark.rapids.tpu.sql.batchSizeRows")
            sess.conf.set(THRESH, 10 * 1024 * 1024)


class TestConditionedJoins:
    def _tables(self, rng, nl=300, nr=200):
        lt = pa.table({
            "k": pa.array(rng.integers(0, 40, nl).astype(np.int64)),
            "a": pa.array(rng.integers(0, 100, nl).astype(np.int64)),
        })
        rt = pa.table({
            "j": pa.array(rng.integers(0, 40, nr).astype(np.int64)),
            "b": pa.array(rng.integers(0, 100, nr).astype(np.int64)),
        })
        return lt, rt

    @pytest.mark.parametrize("how,spark_how", [
        ("left", "left"), ("semi", "left_semi"), ("anti", "left_anti"),
        ("right", "right"), ("full", "full")])
    def test_conditioned_join_types_device(self, sess, rng, how,
                                           spark_how):
        lt, rt = self._tables(rng)
        dl = sess.create_dataframe(lt)
        dr = sess.create_dataframe(rt)
        joined = dl.join(dr, [("k", "j")], spark_how)
        # condition participates in matching: attach via plan (the API
        # route for non-equi conditions)
        joined._plan.condition = (F.col("a") < F.col("b")).expr
        # must stay on device
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
        sess.conf.set(THRESH, -1)
        try:
            got = sorted(joined.collect(),
                         key=lambda r: tuple((x is None, str(x))
                                             for x in r))
        finally:
            sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu",
                          False)
            sess.conf.set(THRESH, 10 * 1024 * 1024)
        want = _brute_join(lt, rt, "k", "j", how,
                           cond=lambda l, r: l["a"] < r["b"])
        assert [tuple(r) for r in got] == [tuple(r) for r in want]

    def test_conditioned_left_broadcast(self, sess, rng):
        lt, rt = self._tables(rng, nl=500, nr=60)
        dl = sess.create_dataframe(lt)
        dr = sess.create_dataframe(rt)
        joined = dl.join(F.broadcast(dr), [("k", "j")], "left")
        joined._plan.condition = (F.col("a") + F.col("b") < 100).expr
        got = sorted(joined.collect(),
                     key=lambda r: tuple((x is None, str(x)) for x in r))
        want = _brute_join(lt, rt, "k", "j", "left",
                           cond=lambda l, r: l["a"] + r["b"] < 100)
        assert [tuple(r) for r in got] == [tuple(r) for r in want]

    def test_conditioned_right_join_device(self, sess, rng):
        """r5: right/full conditioned joins run ON DEVICE via the
        per-build surviving-match channel (VERDICT r4 missing #4;
        GpuHashJoin.scala:104-383 all-types conditional joins)."""
        lt, rt = self._tables(rng, nl=80, nr=120)
        dl = sess.create_dataframe(lt)
        dr = sess.create_dataframe(rt)
        joined = dl.join(dr, [("k", "j")], "right")
        joined._plan.condition = (F.col("a") > F.col("b")).expr
        sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu", True)
        try:
            got = joined.collect()
        finally:
            sess.conf.set("spark.rapids.tpu.test.validateExecsOnTpu",
                          False)
        # oracle via mirrored left join
        want = _brute_join(rt, lt, "j", "k", "left",
                           cond=lambda r, l: l["a"] > r["b"])
        # reorder mirrored columns (right join emits left cols first)
        want = sorted([(w[2], w[3], w[0], w[1]) for w in want],
                      key=lambda r: tuple((x is None, str(x)) for x in r))
        got = sorted(got, key=lambda r: tuple((x is None, str(x))
                                              for x in r))
        assert [tuple(r) for r in got] == [tuple(r) for r in want]

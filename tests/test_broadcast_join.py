"""Broadcast joins: small build side materialized once, probe side streamed.

Reference: GpuBroadcastHashJoinExecBase.scala (equi-join against a broadcast
build), GpuBroadcastNestedLoopJoinExecBase.scala (cross),
GpuBroadcastExchangeExec.scala:352 (the build-side collect), and the
spark.sql.autoBroadcastJoinThreshold selection.

Differential contract: every broadcast plan must match the shuffled plan's
result exactly (threshold=-1 disables broadcast for the oracle run).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql import functions as F

THRESH = "spark.rapids.tpu.sql.autoBroadcastJoinThreshold"


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def _tables(rng, no=300, nl=3000):
    dim = pa.table({
        "d_key": pa.array(np.arange(no)),
        "d_cat": pa.array([f"cat-{i % 7}" for i in range(no)]),
    })
    fact = pa.table({
        "f_key": pa.array(
            [None if i % 19 == 0 else int(v) for i, v in
             enumerate(rng.integers(0, no + 40, nl))], type=pa.int64()),
        "f_val": pa.array(rng.uniform(0.0, 100.0, nl)),
    })
    return dim, fact


def _differential(df, sess):
    got = df.collect()                       # broadcast plan
    sess.conf.set(THRESH, -1)
    want = df.collect()                      # shuffled plan
    sess.conf.set(THRESH, 10 * 1024 * 1024)

    def key(r):
        return tuple((x is None, str(x)) for x in r)
    got = sorted(got, key=key)
    want = sorted(want, key=key)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for gi, wi in zip(g, w):
            if isinstance(wi, float) and gi is not None:
                assert abs(gi - wi) <= 1e-9 * max(1.0, abs(wi)), (g, w)
            else:
                assert gi == wi, (g, w)
    return got


def test_auto_broadcast_small_side(sess, rng):
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    j = dfc.join(dd, [("f_key", "d_key")], "inner")
    phys = sess._plan_physical(j._plan)
    assert "TpuBroadcastHashJoin" in phys.tree_string()
    assert "TpuShuffleExchange" not in phys.tree_string()
    _differential(j, sess)


def test_threshold_disables_auto(sess, rng):
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    sess.conf.set(THRESH, -1)
    phys = sess._plan_physical(
        dfc.join(dd, [("f_key", "d_key")], "inner")._plan)
    sess.conf.set(THRESH, 10 * 1024 * 1024)
    assert "TpuBroadcast" not in phys.tree_string()
    assert "TpuShuffleExchange" in phys.tree_string()


def test_hint_forces_broadcast(sess, rng):
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    sess.conf.set(THRESH, -1)  # auto off: only the hint can select it
    j = dfc.join(F.broadcast(dd), [("f_key", "d_key")], "inner")
    phys = sess._plan_physical(j._plan)
    sess.conf.set(THRESH, 10 * 1024 * 1024)
    assert "TpuBroadcastHashJoin" in phys.tree_string()


def test_hint_survives_pushdown_rebuild(sess, rng):
    """optimize_scans rebuilds Filter/Project nodes; the broadcast hint
    must ride along (it previously vanished, silently shuffling)."""
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    sess.conf.set(THRESH, -1)
    j = dfc.join(F.broadcast(dd.filter(F.col("d_key") >= 0)),
                 [("f_key", "d_key")], "inner")
    phys = sess._plan_physical(j._plan)
    sess.conf.set(THRESH, 10 * 1024 * 1024)
    assert "TpuBroadcastHashJoin" in phys.tree_string()


def test_hint_on_left_inner_side_builds_left(sess, rng):
    """F.broadcast(small).join(big) — the canonical pyspark ordering —
    must broadcast the LEFT side of an inner join (sides are symmetric)."""
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    sess.conf.set(THRESH, -1)
    j = F.broadcast(dd).join(dfc, [("d_key", "f_key")], "inner")
    phys = sess._plan_physical(j._plan)
    sess.conf.set(THRESH, 10 * 1024 * 1024)
    assert "build=left" in phys.tree_string()
    _differential(j, sess)


def test_auto_prefers_smaller_side_inner(sess, rng):
    """Auto selection on an inner join builds the smaller side even when
    it is the left one."""
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    j = dd.join(dfc, [("d_key", "f_key")], "inner")  # small side on LEFT
    phys = sess._plan_physical(j._plan)
    assert "build=left" in phys.tree_string()
    _differential(j, sess)


def test_hint_on_preserved_side_falls_back(sess, rng):
    """A left-outer join cannot broadcast its left (row-preserving) side:
    the hint is refused and the join shuffles (as in Spark)."""
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    j = F.broadcast(dfc).join(dd, [("f_key", "d_key")], "left")
    phys = sess._plan_physical(j._plan)
    assert "TpuBroadcast" not in phys.tree_string()


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_broadcast_join_types_differential(sess, rng, how):
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    j = dfc.join(F.broadcast(dd), [("f_key", "d_key")], how)
    assert "TpuBroadcast" in sess._plan_physical(j._plan).tree_string()
    _differential(j, sess)


def test_broadcast_right_outer(sess, rng):
    """how=right builds the LEFT side — the broadcastable one."""
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    j = F.broadcast(dd).join(dfc, [("d_key", "f_key")], "right")
    tree = sess._plan_physical(j._plan).tree_string()
    assert "build=left" in tree
    _differential(j, sess)


def test_full_outer_never_broadcasts(sess, rng):
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    j = dfc.join(F.broadcast(dd), [("f_key", "d_key")], "full")
    assert "TpuBroadcast" not in sess._plan_physical(j._plan).tree_string()


def test_broadcast_nested_loop_cross(sess, rng):
    small = pa.table({"a": pa.array([1, 2, 3])})
    big = pa.table({"b": pa.array(np.arange(500)),
                    "v": pa.array(rng.uniform(0, 1, 500))})
    ds, db = sess.create_dataframe(small), sess.create_dataframe(big)
    j = db.cross_join(ds)
    tree = sess._plan_physical(j._plan).tree_string()
    assert "TpuBroadcastNestedLoopJoin" in tree
    rows = j.collect()
    assert len(rows) == 1500


def test_broadcast_probe_streams_in_batches(sess, rng):
    """The probe side must NOT materialize wholesale: with a small
    batchSizeRows the probe streams several batches, each joined against
    the one resident build batch."""
    dim, fact = _tables(rng, no=50, nl=4000)
    sess.conf.set("spark.rapids.tpu.sql.batchSizeRows", 1000)
    try:
        dd = sess.create_dataframe(dim)
        dfc = sess.create_dataframe(fact)
        j = dfc.join(F.broadcast(dd), [("f_key", "d_key")], "left")
        _differential(j, sess)
    finally:
        sess.conf.unset("spark.rapids.tpu.sql.batchSizeRows")


def test_broadcast_with_agg_above(sess, rng):
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    df = (dfc.join(F.broadcast(dd), [("f_key", "d_key")], "inner")
          .group_by("d_cat")
          .agg(F.sum(F.col("f_val")).alias("s"),
               F.count_star().alias("c")))
    _differential(df, sess)


def test_fast_path_max_key_with_null_build_row(sess):
    """A legitimate key equal to the dtype max must not collide with the
    fast path's invalid-row sentinel (wrong-results corner found in
    review): the null-key build row must never match, the INT64_MAX row
    must."""
    big = np.iinfo(np.int64).max
    build = pa.table({"k": pa.array([None, big, 5], type=pa.int64()),
                      "b": pa.array([100, 200, 300], type=pa.int64())})
    probe = pa.table({"k": pa.array([big, 5, None, 7], type=pa.int64()),
                      "a": pa.array([1, 2, 3, 4], type=pa.int64())})
    dp = sess.create_dataframe(probe)
    db = sess.create_dataframe(build)
    j = dp.join(F.broadcast(db), on="k", how="left")
    rows = sorted(j.collect(), key=lambda r: (r[1]))
    # (k, a, b): big->200, 5->300, None->null, 7->null
    assert rows[0][1] == 1 and rows[0][2] == 200
    assert rows[1][1] == 2 and rows[1][2] == 300
    assert rows[2][1] == 3 and rows[2][2] is None
    assert rows[3][1] == 4 and rows[3][2] is None


def test_fast_path_nan_keys(sess):
    """NaN == NaN in join keys (Spark semantics) through the sorted-build
    searchsorted kernel."""
    nan = float("nan")
    build = pa.table({"k": pa.array([nan, 2.0, -0.0]),
                      "b": pa.array([10, 20, 30], type=pa.int64())})
    probe = pa.table({"k": pa.array([nan, 0.0, 9.0]),
                      "a": pa.array([1, 2, 3], type=pa.int64())})
    j = sess.create_dataframe(probe).join(
        F.broadcast(sess.create_dataframe(build)), on="k", how="left")
    rows = sorted(j.collect(), key=lambda r: r[1])
    assert rows[0][2] == 10   # NaN matched NaN
    assert rows[1][2] == 30   # 0.0 matched -0.0
    assert rows[2][2] is None


def test_hint_through_filter_above(sess, rng):
    """df.hint('broadcast').filter(...) keeps the hint (ResolvedHint
    survives row-shaping operators in Spark)."""
    dim, fact = _tables(rng)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    sess.conf.set(THRESH, -1)
    hinted = dd.hint("broadcast").filter(F.col("d_key") >= 10)
    j = dfc.join(hinted, [("f_key", "d_key")], "inner")
    phys = sess._plan_physical(j._plan)
    sess.conf.set(THRESH, 10 * 1024 * 1024)
    assert "TpuBroadcastHashJoin" in phys.tree_string()


def test_empty_build_side(sess, rng):
    dim = pa.table({"d_key": pa.array([], type=pa.int64()),
                    "d_cat": pa.array([], type=pa.string())})
    _, fact = _tables(rng, nl=800)
    dd, dfc = sess.create_dataframe(dim), sess.create_dataframe(fact)
    inner = dfc.join(F.broadcast(dd), [("f_key", "d_key")], "inner")
    assert inner.collect() == []
    left = dfc.join(F.broadcast(dd), [("f_key", "d_key")], "left")
    rows = left.collect()
    assert len(rows) == 800
    assert all(r[-1] is None for r in rows)  # d_cat all null


class TestMaskedBuildFallback:
    """r5: broadcast builds keep their selection mask for the dense
    path; when the dense build REJECTS at runtime (duplicate keys) the
    masked build compacts exactly once and the sorted kernel's results
    stay exact — and an all-masked inner build short-circuits empty."""

    def test_dup_key_masked_build_falls_back_exact(self, sess, rng):
        import numpy as np
        import pyarrow as pa
        from spark_rapids_tpu.sql import functions as F
        n_b, n_p = 5000, 20000
        bt = pa.table({
            # duplicate keys -> dense build state rejects (dup > 0)
            "k2": pa.array(rng.integers(0, 500, n_b).astype(np.int64)),
            "w": pa.array(rng.uniform(0, 1, n_b)),
            "flag": pa.array(rng.integers(0, 2, n_b).astype(np.int64)),
        })
        pt = pa.table({
            "k": pa.array(rng.integers(0, 500, n_p).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 1, n_p)),
        })
        # the filter above the broadcast leaves a selection mask
        small = sess.create_dataframe(bt).filter(F.col("flag") == 1)
        big = sess.create_dataframe(pt)
        q = (big.join(F.broadcast(small), on=[("k", "k2")])
             .agg(F.sum(F.col("v") * F.col("w")).alias("s")))
        (got,), = q.collect()
        bp, pp = bt.to_pandas(), pt.to_pandas()
        m = pp.merge(bp[bp.flag == 1], left_on="k", right_on="k2")
        assert abs(got - (m.v * m.w).sum()) < 1e-6

    def test_all_masked_inner_build_short_circuits(self, sess, rng):
        import numpy as np
        import pyarrow as pa
        from spark_rapids_tpu.sql import functions as F
        bt = pa.table({
            "k2": pa.array(rng.integers(0, 50, 500).astype(np.int64)),
            "w": pa.array(rng.uniform(0, 1, 500)),
        })
        pt = pa.table({
            "k": pa.array(rng.integers(0, 50, 2000).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 1, 2000)),
        })
        small = sess.create_dataframe(bt).filter(F.col("w") < -1.0)
        big = sess.create_dataframe(pt)
        q = big.join(F.broadcast(small), on=[("k", "k2")])
        assert q.collect() == []

"""Delta optimistic concurrency (conflict detection + clean retry) and
Change Data Feed.  Reference: delta-lake/ GpuOptimisticTransaction,
OptimisticTransactionImpl conflict rules, CDF write/read."""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.io import delta as D
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess(fresh_session):
    return fresh_session


def _table(n=20, base=0):
    return pa.table({"k": pa.array(np.arange(base, base + n)),
                     "v": pa.array(np.arange(n, dtype=np.float64))})


class TestConcurrency:
    def test_append_loser_retries_cleanly(self, sess, tmp_path):
        """Two appends race for the same version: the loser must land at
        the next version with both commits' rows visible."""
        path = str(tmp_path / "t")
        D.write_delta(sess.create_dataframe(_table()), path)
        v0 = D.DeltaTable(path).version

        # writer A commits version v0+1 while writer B (this thread) has
        # already built its actions against v0: simulate by committing A
        # through the normal API, then committing B with read_version=v0
        D.write_delta(sess.create_dataframe(_table(base=100)), path,
                      mode="append")
        actions = [{"add": {"path": "late.parquet", "partitionValues": {},
                            "size": 1, "modificationTime": 0,
                            "dataChange": True}},
                   {"commitInfo": {"timestamp": 0, "operation": "WRITE"}}]
        import pyarrow.parquet as pq
        pq.write_table(_table(base=200), os.path.join(path, "late.parquet"))
        got = D._commit_with_retry(path, v0, actions, [],
                                   reads_table=False)
        assert got == v0 + 2  # lost v0+1, retried cleanly
        t = D.DeltaTable(path)
        assert len(t.active) == 3

    def test_delete_conflicts_with_concurrent_append(self, sess, tmp_path):
        path = str(tmp_path / "t")
        D.write_delta(sess.create_dataframe(_table()), path)
        v0 = D.DeltaTable(path).version
        # a concurrent append lands first
        D.write_delta(sess.create_dataframe(_table(base=50)), path,
                      mode="append")
        # a DELETE built against v0 must refuse (it did not read the
        # appended file)
        with pytest.raises(D.ConcurrentAppendError):
            D._commit(path, v0, "DELETE",
                      [next(iter(D.DeltaTable(path, version=v0).active))],
                      [])

    def test_remove_same_file_conflicts(self, sess, tmp_path):
        path = str(tmp_path / "t")
        D.write_delta(sess.create_dataframe(_table()), path)
        v0 = D.DeltaTable(path).version
        rel = next(iter(D.DeltaTable(path).active))
        D._commit(path, v0, "DELETE", [rel], [])
        with pytest.raises(D.ConcurrentModificationError):
            D._commit(path, v0, "DELETE", [rel], [])

    def test_version_file_is_create_once(self, sess, tmp_path):
        """The hard-link linearization point: a lost race never
        overwrites the winner's commit file."""
        path = str(tmp_path / "t")
        D.write_delta(sess.create_dataframe(_table()), path)
        log = os.path.join(path, D._LOG_DIR)
        before = open(os.path.join(log, f"{0:020d}.json")).read()
        ok = D._attempt_commit_file(log, 0, [{"commitInfo": {}}])
        assert not ok
        assert open(os.path.join(log, f"{0:020d}.json")).read() == before


class TestCDF:
    def _make(self, sess, tmp_path):
        path = str(tmp_path / "t")
        D.write_delta(sess.create_dataframe(_table()), path,
                      properties={"delta.enableChangeDataFeed": "true"})
        return path

    def test_delete_writes_change_files(self, sess, tmp_path):
        path = self._make(sess, tmp_path)
        v = D.delta_delete(sess, path, F.col("k") < 5)
        cdf = D.table_changes(sess, path, v, v).collect()
        deletes = [r for r in cdf if r[-2] == "delete"]
        assert sorted(r[0] for r in deletes) == [0, 1, 2, 3, 4]
        assert all(r[-1] == v for r in cdf)

    def test_update_pre_and_postimage(self, sess, tmp_path):
        path = self._make(sess, tmp_path)
        v = D.delta_update(sess, path, {"v": F.col("v") + 100.0},
                           condition=F.col("k") == 3)
        rows = D.table_changes(sess, path, v, v).collect()
        kinds = {r[-2]: r[1] for r in rows}
        assert kinds["update_preimage"] == 3.0
        assert kinds["update_postimage"] == 103.0

    def test_inserts_derived_from_appends(self, sess, tmp_path):
        path = self._make(sess, tmp_path)
        v = D.write_delta(sess.create_dataframe(_table(n=3, base=900)),
                          path, mode="append")
        rows = D.table_changes(sess, path, v, v).collect()
        assert sorted(r[0] for r in rows) == [900, 901, 902]
        assert all(r[-2] == "insert" for r in rows)

    def test_full_history_range(self, sess, tmp_path):
        path = self._make(sess, tmp_path)
        D.write_delta(sess.create_dataframe(_table(n=2, base=500)), path,
                      mode="append")
        D.delta_delete(sess, path, F.col("k") == 500)
        rows = D.table_changes(sess, path, 1).collect()
        types = sorted({r[-2] for r in rows})
        assert types == ["delete", "insert"]

    def test_mutation_without_cdf_raises_on_read(self, sess, tmp_path):
        path = str(tmp_path / "t")
        D.write_delta(sess.create_dataframe(_table()), path)  # CDF off
        v = D.delta_delete(sess, path, F.col("k") < 3)
        with pytest.raises(ValueError, match="CDF"):
            D.table_changes(sess, path, v, v).collect()

    def test_dv_delete_cdf(self, sess, tmp_path):
        path = self._make(sess, tmp_path)
        v = D.delta_delete(sess, path, F.col("k") >= 18, use_dv=True)
        rows = D.table_changes(sess, path, v, v).collect()
        assert sorted(r[0] for r in rows) == [18, 19]
        assert all(r[-2] == "delete" for r in rows)

// spark_rapids_tpu native companion library.
//
// TPU-native analog of the reference's native layer (SURVEY §2.9): the
// pieces the reference gets from spark-rapids-jni / nvcomp that are host-side
// here because the device side is XLA:
//
//   * Spark-exact murmur3 / xxhash64 batch kernels (spark-rapids-jni `Hash`;
//     sql-plugin uses them for hash partitioning).  The JAX device kernels in
//     ops/hashing.py stay the device path; these are the host path (shuffle
//     writers, CPU fallback partitioning) and the cross-check oracle.
//   * A block compression codec for spill/shuffle payloads (nvcomp LZ4
//     analog).  LZ77-family byte codec, self-describing frames; host-side
//     because TPU spill tiers are host RAM + disk (no GDS analog).
//   * Spark-exact string→number casts over Arrow offsets+bytes layout
//     (spark-rapids-jni `CastStrings` analog).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <cstdlib>

extern "C" {

// ---------------------------------------------------------------------------
// murmur3 (x86_32, Spark seed handling) — matches
// org.apache.spark.sql.catalyst.expressions.Murmur3HashFunction for LONG
// columns: each long hashed as two little-endian 32-bit halves.
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_k1(uint32_t k1) {
  k1 *= 0xcc9e2d51u;
  k1 = rotl32(k1, 15);
  k1 *= 0x1b873593u;
  return k1;
}

static inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  h1 = h1 * 5u + 0xe6546b64u;
  return h1;
}

static inline uint32_t fmix(uint32_t h1, uint32_t len) {
  h1 ^= len;
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

// Hash n int64 values (Spark hashLong): seed per row from `seeds`, result
// int32 per row.  Nulls: caller passes the previous hash as seed and skips
// (Spark: null columns leave the running hash unchanged).
void srt_murmur3_long(const int64_t* vals, const int32_t* seeds,
                      int32_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = (uint64_t)vals[i];
    uint32_t h1 = (uint32_t)seeds[i];
    h1 = mix_h1(h1, mix_k1((uint32_t)(v & 0xffffffffu)));
    h1 = mix_h1(h1, mix_k1((uint32_t)(v >> 32)));
    out[i] = (int32_t)fmix(h1, 8);
  }
}

// Hash n utf8 strings in Arrow layout (Spark hashUnsafeBytes over int-sized
// chunks then tail bytes — matches Murmur3HashFunction for UTF8String).
void srt_murmur3_utf8(const uint8_t* bytes, const int64_t* offsets,
                      const int32_t* seeds, int32_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = bytes + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    uint32_t h1 = (uint32_t)seeds[i];
    int64_t nblocks = len / 4;
    for (int64_t b = 0; b < nblocks; ++b) {
      uint32_t k1;
      memcpy(&k1, p + b * 4, 4);  // little-endian load (Spark Platform.getInt)
      h1 = mix_h1(h1, mix_k1(k1));
    }
    // Spark's tail: each remaining BYTE hashed as its own int (sign-extended)
    for (int64_t b = nblocks * 4; b < len; ++b) {
      int32_t k1 = (int8_t)p[b];
      h1 = mix_h1(h1, mix_k1((uint32_t)k1));
    }
    out[i] = (int32_t)fmix(h1, (uint32_t)len);
  }
}

// Spark's pmod partition id from a hash.
void srt_pmod_partition(const int32_t* hashes, int32_t num_parts,
                        int32_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    int32_t m = hashes[i] % num_parts;
    out[i] = m < 0 ? m + num_parts : m;
  }
}

// ---------------------------------------------------------------------------
// xxhash64 (Spark XxHash64Function, seed 42) for int64 values.
// ---------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

// Matches Spark's XXH64.hashLong == canonical xxhash64 over the long's
// little-endian bytes (verified vs python-xxhash).
void srt_xxhash64_long(const int64_t* vals, const int64_t* seeds,
                       int64_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t seed = (uint64_t)seeds[i];
    uint64_t hash = seed + P5 + 8;
    uint64_t k1 = (uint64_t)vals[i] * P2;
    k1 = rotl64(k1, 31);
    k1 *= P1;
    hash ^= k1;
    hash = rotl64(hash, 27) * P1 + P4;
    hash ^= hash >> 33;
    hash *= P2;
    hash ^= hash >> 29;
    hash *= P3;
    hash ^= hash >> 32;
    out[i] = (int64_t)hash;
  }
}

// ---------------------------------------------------------------------------
// Block codec (nvcomp-LZ4 analog for spill/shuffle payloads).
// Greedy LZ77 with a 64Ki hash table; frame = varint raw_len then tokens:
//   literal run: [len:varint][bytes]
//   match:       [0x00][offset:varint][len-4:varint]   (min match 4)
// A literal run never starts with 0x00 token ambiguity because literal run
// tokens carry length+1 (so token>=1); 0 marks a match.
// ---------------------------------------------------------------------------

static inline int put_varint(uint8_t* dst, uint64_t v) {
  int k = 0;
  while (v >= 0x80) { dst[k++] = (uint8_t)(v | 0x80); v >>= 7; }
  dst[k++] = (uint8_t)v;
  return k;
}

static inline int get_varint(const uint8_t* src, uint64_t* v) {
  int k = 0; uint64_t out = 0; int shift = 0;
  while (true) {
    uint8_t b = src[k++];
    out |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *v = out;
  return k;
}

int64_t srt_compress_bound(int64_t n) { return n + n / 16 + 64; }

// Returns compressed size, or -1 if dst too small.
int64_t srt_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                     int64_t dst_cap) {
  const int HBITS = 16;
  static thread_local int64_t* table = nullptr;
  if (!table) table = (int64_t*)malloc(sizeof(int64_t) << HBITS);
  memset(table, 0xff, sizeof(int64_t) << HBITS);

  int64_t d = 0;
  if (d + 10 > dst_cap) return -1;
  d += put_varint(dst + d, (uint64_t)n);
  int64_t i = 0, lit_start = 0;
  while (i + 4 <= n) {
    uint32_t w;
    memcpy(&w, src + i, 4);
    uint32_t h = (w * 2654435761u) >> (32 - HBITS);
    int64_t cand = table[h];
    table[h] = i;
    uint32_t cw;
    if (cand >= 0 && i - cand < (1 << 20) &&
        (memcpy(&cw, src + cand, 4), cw == w)) {
      // flush literals
      int64_t lit = i - lit_start;
      if (lit > 0) {
        if (d + 10 + lit > dst_cap) return -1;
        d += put_varint(dst + d, (uint64_t)lit + 1);
        memcpy(dst + d, src + lit_start, lit);
        d += lit;
      }
      int64_t len = 4;
      while (i + len < n && src[cand + len] == src[i + len]) ++len;
      if (d + 20 > dst_cap) return -1;
      dst[d++] = 0x00;
      d += put_varint(dst + d, (uint64_t)(i - cand));
      d += put_varint(dst + d, (uint64_t)(len - 4));
      i += len;
      lit_start = i;
    } else {
      ++i;
    }
  }
  int64_t lit = n - lit_start;
  if (lit > 0) {
    if (d + 10 + lit > dst_cap) return -1;
    d += put_varint(dst + d, (uint64_t)lit + 1);
    memcpy(dst + d, src + lit_start, lit);
    d += lit;
  }
  return d;
}

// Returns decompressed size, or -1 on malformed input / overflow.
int64_t srt_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                       int64_t dst_cap) {
  int64_t s = 0, d = 0;
  uint64_t raw_len;
  s += get_varint(src + s, &raw_len);
  if ((int64_t)raw_len > dst_cap) return -1;
  while (s < n && d < (int64_t)raw_len) {
    uint64_t tok;
    s += get_varint(src + s, &tok);
    if (tok == 0) {  // match
      uint64_t off, mlen;
      s += get_varint(src + s, &off);
      s += get_varint(src + s, &mlen);
      mlen += 4;
      if (off == 0 || (int64_t)off > d || d + (int64_t)mlen > (int64_t)raw_len)
        return -1;
      // byte-wise: overlapping copies are valid (run-length style)
      for (uint64_t b = 0; b < mlen; ++b) dst[d + b] = dst[d - off + b];
      d += mlen;
    } else {  // literal run of (tok-1) bytes
      uint64_t lit = tok - 1;
      if (s + (int64_t)lit > n || d + (int64_t)lit > (int64_t)raw_len)
        return -1;
      memcpy(dst + d, src + s, lit);
      s += lit;
      d += lit;
    }
  }
  return d == (int64_t)raw_len ? d : -1;
}

// ---------------------------------------------------------------------------
// String→number casts over Arrow offsets+bytes (CastStrings analog).
// Spark semantics: trim ASCII whitespace; invalid/overflow → null.
// ---------------------------------------------------------------------------

// out_valid[i] = 1 if parsed, 0 if null (invalid).  Input validity handled
// by the caller (null in → null out).
void srt_cast_string_to_long(const uint8_t* bytes, const int64_t* offsets,
                             int64_t* out, uint8_t* out_valid, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = bytes + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int64_t a = 0, b = len;
    while (a < b && (p[a] == ' ' || (p[a] >= 9 && p[a] <= 13))) ++a;
    while (b > a && (p[b - 1] == ' ' || (p[b - 1] >= 9 && p[b - 1] <= 13)))
      --b;
    out_valid[i] = 0;
    out[i] = 0;
    if (a >= b) continue;
    bool neg = false;
    if (p[a] == '+' || p[a] == '-') { neg = p[a] == '-'; ++a; }
    if (a >= b) continue;
    uint64_t acc = 0;
    // overflow bound: 2^63 for negatives (LONG_MIN parses), 2^63-1 else
    uint64_t limit = neg ? 0x8000000000000000ULL : 0x7fffffffffffffffULL;
    bool ok = true;
    for (int64_t k = a; k < b; ++k) {
      if (p[k] < '0' || p[k] > '9') { ok = false; break; }
      uint64_t digit = (uint64_t)(p[k] - '0');
      if (acc > (limit - digit) / 10) { ok = false; break; }
      acc = acc * 10 + digit;
    }
    if (!ok) continue;
    out[i] = neg ? (int64_t)(~acc + 1) : (int64_t)acc;
    out_valid[i] = 1;
  }
}

void srt_cast_string_to_double(const uint8_t* bytes, const int64_t* offsets,
                               double* out, uint8_t* out_valid, int64_t n) {
  char buf[64];
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = bytes + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int64_t a = 0, b = len;
    while (a < b && (p[a] == ' ' || (p[a] >= 9 && p[a] <= 13))) ++a;
    while (b > a && (p[b - 1] == ' ' || (p[b - 1] >= 9 && p[b - 1] <= 13)))
      --b;
    out_valid[i] = 0;
    out[i] = 0.0;
    int64_t m = b - a;
    if (m <= 0 || m >= (int64_t)sizeof(buf)) continue;
    memcpy(buf, p + a, m);
    buf[m] = '\0';
    char* end = nullptr;
    double v = strtod(buf, &end);
    if (end == buf + m) {
      out[i] = v;
      out_valid[i] = 1;
    }
  }
}

}  // extern "C"
